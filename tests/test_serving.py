"""apex_tpu.serving tests (tier-1, CPU): paged KV-cache correctness,
decode parity vs the full-sequence forward, continuous batching with
staggered arrivals/EOS under the two-program compilation contract,
sampling determinism, and a tp=2 decode smoke."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import GPTConfig, GPTLMHeadModel
from apex_tpu.serving import (
    BlockAllocator,
    CacheOutOfBlocks,
    EngineConfig,
    InferenceEngine,
    KVCache,
    Request,
    SamplingParams,
    blocks_needed,
    defragment,
    device_block_table,
    gather_kv,
    paged_write,
    sample_tokens,
)


def _tiny_model(**kw):
    kw.setdefault("dropout", 0.0)
    kw.setdefault("remat", False)
    cfg = GPTConfig.tiny(**kw)
    model = GPTLMHeadModel(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    return cfg, model, params


def _ids(B, S, vocab=128, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randint(0, vocab, (B, S)))


# ---------------------------------------------------------------------------
# block allocator + paged write/read primitives
# ---------------------------------------------------------------------------

def test_block_allocator_alloc_free_defrag_accounting():
    a = BlockAllocator(8)
    assert a.num_free == 8 and a.num_used == 0
    first = a.alloc(3)
    assert sorted(first) == [0, 1, 2]      # low ids served first
    assert a.num_used == 3
    assert a.utilization == pytest.approx(3 / 8)
    a.free([first[1]])
    assert a.num_free == 6
    with pytest.raises(ValueError, match="double free"):
        a.free([first[0], first[0]])
    with pytest.raises(CacheOutOfBlocks):
        a.alloc(100)
    assert blocks_needed(17, 8) == 3 and blocks_needed(16, 8) == 2


def test_paged_write_and_gather_roundtrip():
    """Tokens written through a (deliberately scrambled) block table must
    come back in position order; invalid positions must write nothing."""
    L, N, bs, H, D = 2, 6, 4, 2, 3
    cache = KVCache.create(L, N, bs, H, D, dtype=jnp.float32)
    B, S = 2, 10   # spans 3 blocks per sequence
    rng = np.random.RandomState(0)
    vals = jnp.asarray(rng.randn(B, S, H, D).astype("f4"))
    tables = np.array([[5, 0, 3, -1], [2, 4, 1, -1]], np.int32)
    dtbl = device_block_table(tables, N)
    pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
    seq_lens = jnp.asarray([10, 7], jnp.int32)   # row 1: tail is padding
    valid = pos < seq_lens[:, None]
    k = paged_write(cache.k, 1, dtbl, pos, vals, valid)

    out = gather_kv(k, 1, dtbl)                  # [B, 4*bs, H, D]
    np.testing.assert_array_equal(np.asarray(out[0, :10]),
                                  np.asarray(vals[0]))
    np.testing.assert_array_equal(np.asarray(out[1, :7]),
                                  np.asarray(vals[1, :7]))
    # the padding positions of row 1 were dropped, not written
    np.testing.assert_array_equal(np.asarray(out[1, 7:10]),
                                  np.zeros((3, H, D), np.float32))
    # layer 0 untouched
    assert float(jnp.max(jnp.abs(k[0]))) == 0.0


def test_defragment_compacts_and_preserves_contents():
    L, N, bs, H, D = 1, 16, 4, 2, 2
    cache = KVCache.create(L, N, bs, H, D, dtype=jnp.float32)
    alloc = BlockAllocator(N)
    rng = np.random.RandomState(1)
    tables = np.full((2, 4), -1, np.int32)
    # interleave allocations from two sequences, then free a third to
    # checkerboard the pool
    other = alloc.alloc(2)
    tables[0, :2] = alloc.alloc(2)
    tables[1, :3] = alloc.alloc(3)
    alloc.free(other)
    vals = [jnp.asarray(rng.randn(1, 8, H, D).astype("f4")),
            jnp.asarray(rng.randn(1, 12, H, D).astype("f4"))]
    for b, (n_tok, v) in enumerate([(8, vals[0]), (12, vals[1])]):
        pos = jnp.arange(n_tok, dtype=jnp.int32)[None]
        k = paged_write(cache.k, 0, device_block_table(tables[b:b + 1], N),
                        pos, v, jnp.ones((1, n_tok), bool))
        cache = cache._replace(k=k)

    before = [np.asarray(gather_kv(cache.k, 0,
                                   device_block_table(tables[b:b + 1], N)))
              for b in range(2)]
    cache2, tables2 = defragment(cache, alloc, tables)
    # live blocks now occupy the low indices, free list is the tail
    assert set(tables2[tables2 >= 0].ravel()) == set(range(5))
    assert alloc.num_free == N - 5
    for b in range(2):
        after = np.asarray(gather_kv(
            cache2.k, 0, device_block_table(tables2[b:b + 1], N)))
        np.testing.assert_array_equal(after, before[b])
    # and the pool still allocates from the compacted tail
    assert sorted(alloc.alloc(2)) == [5, 6]


def test_kv_dtype_follows_amp_policy():
    from apex_tpu.amp import _amp_state
    from apex_tpu.serving import default_kv_dtype

    saved = _amp_state._amp_state.handle
    try:
        _amp_state._amp_state.handle = None
        assert default_kv_dtype() == jnp.dtype(jnp.float32)
        assert default_kv_dtype(jnp.bfloat16) == jnp.dtype(jnp.bfloat16)

        import apex_tpu.amp as amp
        from apex_tpu.optimizers import FusedAdam

        params = {"w": jnp.ones((4, 4), jnp.float32)}
        _, _, handle = amp.initialize(params, FusedAdam(), opt_level="O2",
                                      verbosity=0)
        assert default_kv_dtype() == jnp.dtype(jnp.bfloat16)
        # explicit dtype overrides the policy
        assert default_kv_dtype(jnp.float32) == jnp.dtype(jnp.float32)
        cache = KVCache.create(1, 2, 4, 2, 2)
        assert cache.k.dtype == jnp.bfloat16
    finally:
        _amp_state._amp_state.handle = saved


# ---------------------------------------------------------------------------
# decode parity vs the full-sequence forward (acceptance criterion)
# ---------------------------------------------------------------------------

def test_decode_with_paged_cache_matches_full_forward():
    """Prefill + one-token-at-a-time decode through the paged cache must
    reproduce the full-sequence forward's logits to <= 1e-5 (fp32,
    2-layer GPT) — including ragged prompts (per-row padding)."""
    cfg, model, params = _tiny_model()
    B, S, pre = 2, 24, 16
    ids = _ids(B, S)
    ref = model.apply(params, ids)

    N, bs = 32, 8
    cache = KVCache.create(cfg.num_layers, N, bs, cfg.num_heads,
                           cfg.hidden_size // cfg.num_heads,
                           dtype=jnp.float32)
    alloc = BlockAllocator(N)
    tables = np.full((B, 8), -1, np.int32)
    for b in range(B):
        tables[b, :blocks_needed(S, bs)] = alloc.alloc(blocks_needed(S, bs))
    dtbl = device_block_table(tables, N)

    pos = jnp.broadcast_to(jnp.arange(pre, dtype=jnp.int32)[None], (B, pre))
    logits, cache = model.apply(
        params, ids[:, :pre], kv_cache=cache, block_tables=dtbl,
        cache_positions=pos, seq_lens=jnp.full((B,), pre, jnp.int32))
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref[:, :pre]),
                               atol=1e-5, rtol=0)

    for t in range(pre, S):
        step, cache = model.apply(
            params, ids[:, t:t + 1], kv_cache=cache, block_tables=dtbl,
            cache_positions=jnp.full((B, 1), t, jnp.int32),
            seq_lens=jnp.full((B,), t + 1, jnp.int32))
        np.testing.assert_allclose(np.asarray(step[:, 0]),
                                   np.asarray(ref[:, t]),
                                   atol=1e-5, rtol=0)


def test_ragged_prefill_masks_padding():
    """A right-padded prefill batch must produce, at each row's true
    positions, the logits of that row's unpadded forward."""
    cfg, model, params = _tiny_model()
    lens = [5, 11]
    P = 16
    ids = _ids(2, P, seed=3)
    N, bs = 16, 4
    cache = KVCache.create(cfg.num_layers, N, bs, cfg.num_heads,
                           cfg.hidden_size // cfg.num_heads,
                           dtype=jnp.float32)
    alloc = BlockAllocator(N)
    tables = np.full((2, 4), -1, np.int32)
    for b, n in enumerate(lens):
        tables[b, :blocks_needed(n, bs)] = alloc.alloc(blocks_needed(n, bs))
    pos = jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32)[None], (2, P))
    logits, _ = model.apply(
        params, ids, kv_cache=cache,
        block_tables=device_block_table(tables, N),
        cache_positions=pos, seq_lens=jnp.asarray(lens, jnp.int32))
    for b, n in enumerate(lens):
        solo = model.apply(params, ids[b:b + 1, :n])
        np.testing.assert_allclose(np.asarray(logits[b, :n]),
                                   np.asarray(solo[0]), atol=1e-5, rtol=0)


# ---------------------------------------------------------------------------
# continuous batching engine (acceptance criterion: 8 staggered requests,
# exactly two jit compilations)
# ---------------------------------------------------------------------------

def _build_engine(seed=0, **cfg_kw):
    cfg, model, params = _tiny_model()
    ecfg = EngineConfig(max_batch=4, block_size=8, num_blocks=64,
                        max_prefill_len=16, max_seq_len=64, seed=seed,
                        **cfg_kw)
    return InferenceEngine(model, params, ecfg)


def _staggered_workload(engine):
    """8 requests: 4 up front, 2 scheduler ticks, 4 late arrivals —
    different prompt lengths, generation budgets, and samplers."""
    rng = np.random.RandomState(7)
    reqs = []
    for i in range(8):
        samp = (SamplingParams() if i % 2 == 0 else
                SamplingParams(temperature=0.7, top_k=10, top_p=0.9))
        reqs.append(Request(uid=f"r{i}",
                            prompt=list(rng.randint(0, 128, 3 + i)),
                            max_new_tokens=2 + (i % 4) * 3,
                            sampling=samp))
    for r in reqs[:4]:
        engine.add_request(r)
    engine.step()
    engine.step()
    for r in reqs[4:]:
        engine.add_request(r)
    out = engine.run()
    return reqs, out


def test_continuous_batching_staggered_two_compilations():
    engine = _build_engine()
    reqs, out = _staggered_workload(engine)
    assert set(out) == {r.uid for r in reqs}
    for r in reqs:
        assert len(out[r.uid]) == r.max_new_tokens
        assert all(0 <= t < 128 for t in out[r.uid])
    stats = engine.stats()
    # THE two-program contract: one prefill shape, one decode shape
    assert stats["prefill_compilations"] == 1
    assert stats["decode_compilations"] == 1
    assert stats["num_prefills"] == 8
    # every slot and every block was handed back
    assert stats["active_slots"] == 0
    assert engine.allocator.num_used == 0


def test_engine_is_deterministic_under_a_fixed_seed():
    _, out1 = _staggered_workload(_build_engine(seed=123))
    _, out2 = _staggered_workload(_build_engine(seed=123))
    assert out1 == out2
    # and the sampled half actually depends on the seed
    _, out3 = _staggered_workload(_build_engine(seed=456))
    sampled = [f"r{i}" for i in range(8) if i % 2 == 1]
    assert any(out1[u] != out3[u] for u in sampled)


def test_engine_eos_evicts_early():
    """A request whose eos_token_id equals the token greedy decoding
    actually produces must stop at that token, well before its
    max_new_tokens budget."""
    prompt = list(np.random.RandomState(3).randint(0, 128, 6))
    pilot = _build_engine()
    pilot.add_request(Request(uid="p", prompt=prompt, max_new_tokens=8))
    first = pilot.run()["p"][0]

    engine = _build_engine()
    engine.add_request(Request(uid="q", prompt=prompt, max_new_tokens=8,
                               eos_token_id=int(first)))
    out = engine.run()["q"]
    assert out == [first]
    assert engine.allocator.num_used == 0


def test_engine_admission_control_and_validation():
    engine = _build_engine()
    with pytest.raises(ValueError, match="max_prefill_len"):
        engine.add_request(Request(uid="long", prompt=list(range(17))))
    with pytest.raises(ValueError, match="max_seq_len"):
        engine.add_request(Request(uid="deep", prompt=[1] * 8,
                                   max_new_tokens=100))
    with pytest.raises(ValueError, match="empty prompt"):
        engine.add_request(Request(uid="empty", prompt=[]))
    with pytest.raises(ValueError, match="max_new_tokens"):
        engine.add_request(Request(uid="zero", prompt=[1],
                                   max_new_tokens=0))
    with pytest.raises(ValueError, match="top_p"):
        engine.add_request(Request(uid="bad", prompt=[1],
                                   sampling=SamplingParams(top_p=0.0)))


def test_engine_admission_reserves_worst_case_blocks():
    """Two long-budget requests whose worst cases together exceed the
    pool must be serialized by admission (second queued until the first
    finishes) — never admitted together and crashed mid-decode."""
    cfg, model, params = _tiny_model()
    # pool of 5 blocks; each request's worst case is 8+24=32 tokens ->
    # 4 blocks, so only one fits at a time
    engine = InferenceEngine(model, params, EngineConfig(
        max_batch=2, block_size=8, num_blocks=5, max_prefill_len=8,
        max_seq_len=32))
    for uid in ("a", "b"):
        engine.add_request(Request(uid=uid, prompt=[1, 2, 3, 4, 5, 6, 7, 8],
                                   max_new_tokens=24))
    engine.step()
    assert engine.stats()["active_slots"] == 1
    assert engine.stats()["waiting"] == 1
    out = engine.run()
    assert sorted(out) == ["a", "b"]
    assert all(len(v) == 24 for v in out.values())
    assert engine.allocator.num_used == 0


def test_engine_raises_when_pool_can_never_serve_the_queue():
    """A request whose prompt needs more blocks than the whole pool must
    raise CacheOutOfBlocks instead of spinning the scheduler forever."""
    cfg, model, params = _tiny_model()
    engine = InferenceEngine(model, params, EngineConfig(
        max_batch=2, block_size=8, num_blocks=2, max_prefill_len=16,
        max_seq_len=32))
    engine.add_request(Request(uid="big", prompt=[1] * 16,
                               max_new_tokens=2))
    with pytest.raises(CacheOutOfBlocks):
        engine.run()


# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------

def test_sampling_greedy_topk_topp_determinism():
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(4, 64).astype("f4") * 2.0)
    key = jax.random.PRNGKey(42)
    ones = jnp.ones((4,), jnp.float32)
    zeros_i = jnp.zeros((4,), jnp.int32)

    # temperature <= 0: exact argmax
    toks = sample_tokens(logits, key, jnp.zeros((4,)), zeros_i, ones)
    np.testing.assert_array_equal(np.asarray(toks),
                                  np.asarray(jnp.argmax(logits, -1)))
    # top_k = 1 is greedy regardless of temperature
    toks = sample_tokens(logits, key, ones * 5.0,
                         jnp.ones((4,), jnp.int32), ones)
    np.testing.assert_array_equal(np.asarray(toks),
                                  np.asarray(jnp.argmax(logits, -1)))
    # a vanishing nucleus keeps only the argmax token
    toks = sample_tokens(logits, key, ones, zeros_i, ones * 1e-6)
    np.testing.assert_array_equal(np.asarray(toks),
                                  np.asarray(jnp.argmax(logits, -1)))
    # fixed key -> identical draws; different key -> (some) different
    a = sample_tokens(logits, key, ones, zeros_i, ones)
    b = sample_tokens(logits, key, ones, zeros_i, ones)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    draws = np.stack([
        np.asarray(sample_tokens(logits, jax.random.PRNGKey(s), ones * 2.0,
                                 zeros_i, ones))
        for s in range(16)])
    assert len(np.unique(draws)) > 1

    # top-k draws stay inside the k most likely tokens
    k = 5
    topk_sets = np.asarray(jnp.argsort(-logits, axis=-1)[:, :k])
    for s in range(16):
        toks = np.asarray(sample_tokens(
            logits, jax.random.PRNGKey(s), ones * 3.0,
            jnp.full((4,), k, jnp.int32), ones))
        for row in range(4):
            assert toks[row] in topk_sets[row]


def test_sampling_top_p_renormalizes_over_top_k_survivors():
    """The documented composition: top-p mass is measured over the
    RENORMALIZED top-k distribution. Logits (3.0, 1.9, rest 1.0):
    within top-2 token 0 holds e^3/(e^3+e^1.9) ~ 0.75 of the mass, so
    top_p=0.7 must always return token 0 — while over the full
    vocabulary token 0 holds only ~0.10, under which token 1 would
    (wrongly) stay sampleable ~25% of draws."""
    logits = np.full((1, 64), 1.0, np.float32)
    logits[0, 0], logits[0, 1] = 3.0, 1.9
    logits = jnp.asarray(logits)
    ones = jnp.ones((1,), jnp.float32)
    for s in range(32):
        tok = int(sample_tokens(logits, jax.random.PRNGKey(s),
                                ones, jnp.full((1,), 2, jnp.int32),
                                ones * 0.7)[0])
        assert tok == 0


# ---------------------------------------------------------------------------
# tensor-parallel decode smoke (tp=2, heads sharded over the mesh)
# ---------------------------------------------------------------------------

def test_tp2_paged_decode_smoke():
    """Decode attention + the row-parallel output projection under a
    2-way tensor mesh (heads sharded, partial products psum'd — the
    Megatron decomposition) must match the unsharded computation."""
    try:
        shard_map = jax.shard_map
    except AttributeError:
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from apex_tpu.ops.flash_attention import paged_decode_attention

    B, H, D, N, bs, M = 2, 4, 8, 8, 4, 3
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, D).astype("f4"))
    k_pages = jnp.asarray(rng.randn(N, bs, H, D).astype("f4"))
    v_pages = jnp.asarray(rng.randn(N, bs, H, D).astype("f4"))
    w_out = jnp.asarray(rng.randn(H * D, 16).astype("f4") * 0.1)
    tables = jnp.asarray([[0, 2, 5], [1, 3, 4]], jnp.int32)
    ctx = jnp.asarray([9, 6], jnp.int32)
    scale = 1.0 / np.sqrt(D)

    def attend_project(q, kp, vp, w):
        out = paged_decode_attention(q, kp, vp, tables, ctx, scale)
        y = out.reshape(B, -1) @ w          # local heads' slice of W_out
        return jax.lax.psum(y, "tensor")    # row-parallel reduction

    ref = (paged_decode_attention(q, k_pages, v_pages, tables, ctx, scale)
           .reshape(B, -1) @ w_out)

    mesh = jax.make_mesh((2,), ("tensor",))
    # heads shard over the mesh; W_out rows shard to match (head-major
    # flat layout keeps rank r's rows contiguous)
    w_sharded = w_out.reshape(H, D, 16)
    got = jax.jit(shard_map(
        lambda q, kp, vp, w: attend_project(q, kp, vp,
                                            w.reshape(-1, w.shape[-1])),
        mesh=mesh,
        in_specs=(P(None, "tensor"), P(None, None, "tensor"),
                  P(None, None, "tensor"), P("tensor")),
        out_specs=P(),
        check_rep=False,
    ))(q, k_pages, v_pages, w_sharded)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)
