"""Native apex_C analog + profiler-surface tests (SURVEY.md §2.2
``apex_C`` row; §5 tracing row)."""

import os

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import _native, profiler


def _arrays():
    rng = np.random.RandomState(0)
    return [rng.randn(4, 5).astype("f4"),
            rng.randint(0, 100, (7,)).astype("i4"),
            rng.randn(2, 3, 2).astype("f8"),
            np.asarray(3.5, "f4")]


def test_native_extension_builds_and_loads():
    """The C extension compiles with the baked-in toolchain (gcc is in
    the image); the fallback path is exercised separately."""
    assert _native.native_available()


def test_flatten_unflatten_roundtrip_native():
    arrays = _arrays()
    flat, metas = _native.flatten(arrays)
    assert flat.dtype == np.uint8
    assert flat.nbytes == sum(a.nbytes for a in arrays)
    back = _native.unflatten(flat, metas)
    for a, b in zip(arrays, back):
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(a, b)


def test_flatten_unflatten_fallback_matches_native(monkeypatch):
    arrays = _arrays()
    flat_n, metas = _native.flatten(arrays)
    # force the numpy fallback
    monkeypatch.setattr(_native, "_LIB", None)
    monkeypatch.setattr(_native, "_TRIED", True)
    flat_f, metas_f = _native.flatten(arrays)
    np.testing.assert_array_equal(flat_n, flat_f)
    back = _native.unflatten(flat_f, metas_f)
    for a, b in zip(arrays, back):
        np.testing.assert_array_equal(a, b)


def test_flatten_empty():
    flat, metas = _native.flatten([])
    assert flat.size == 0 and metas == []
    assert _native.unflatten(flat, metas) == []


def test_step_timer():
    t = profiler.StepTimer(warmup=1)
    x = jnp.ones((8, 8))
    for _ in range(5):
        x = (x @ x) / 8.0
        t.tick(x)
    s = t.summary()
    assert s["steps"] == 3  # 5 ticks -> 4 intervals -> 1 warmup dropped
    assert s["mean_ms"] >= 0.0 and s["min_ms"] <= s["max_ms"]
    t.reset()
    assert t.summary() == {"steps": 0}


def test_annotate_and_trace(tmp_path):
    with profiler.annotate("unit-test-region"):
        jnp.sum(jnp.ones((4,))).block_until_ready()
    d = str(tmp_path / "trace")
    try:
        with profiler.trace(d):
            jnp.sum(jnp.ones((4,))).block_until_ready()
    except Exception:
        return  # profiler unavailable on this runtime: surface is optional
    assert os.path.isdir(d)
