"""Overload-protection certification (tier-1, CPU): the ISSUE 8 layer.

Priority admission, bounded-queue backpressure (``QueueFullError`` /
``try_add``), the admit-time feasibility gate (deadline-aware shedding
with status ``"rejected"``), priority-aware preemption, and the
degradation ladder (speculation suspension -> prefix-cache flush ->
lowest-class admission pause) — each held to the determinism bar the
scheduler has carried since PR 2/3: priorities and ladder transitions
are pure SCHEDULE changes, and sampling is schedule-invariant, so
per-request outputs never depend on them (uniform-priority traffic is
bit-identical to the pre-priority FIFO engine)."""

import json
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import GPTConfig, GPTLMHeadModel
from apex_tpu.serving import (
    EngineConfig,
    InferenceEngine,
    QueueFullError,
    Request,
    SamplingParams,
)

# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_gpt():
    cfg = GPTConfig.tiny(dropout=0.0, remat=False)
    model = GPTLMHeadModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    return model, params


ENGINE_KW = dict(max_batch=2, block_size=4, num_blocks=32,
                 max_prefill_len=8, max_seq_len=32, seed=7)


def _mk(tiny_gpt, clock=None, **overrides):
    model, params = tiny_gpt
    kw = dict(ENGINE_KW)
    kw.update(overrides)
    return InferenceEngine(model, params, EngineConfig(**kw),
                           clock=clock)


def _req(uid, seed=0, n=5, new=4, **kw):
    prompt = list(np.random.RandomState(seed).randint(1, 100, n))
    return Request(uid, prompt, max_new_tokens=new, **kw)


# ---------------------------------------------------------------------------
# satellite: duplicate-uid rejection
# ---------------------------------------------------------------------------


def test_add_request_rejects_duplicate_uid(tiny_gpt):
    engine = _mk(tiny_gpt)
    engine.add_request(_req("a"))
    # duplicate while WAITING: the uid-keyed deadline map and the
    # engine-owned status field would silently collide
    with pytest.raises(ValueError, match="already waiting or resident"):
        engine.add_request(_req("a", seed=1))
    engine.step()   # "a" becomes resident
    assert any(s is not None and s.request.uid == "a"
               for s in engine.slots)
    with pytest.raises(ValueError, match="already waiting or resident"):
        engine.add_request(_req("a", seed=2))
    out = engine.run()
    assert len(out["a"]) == 4
    # a FINISHED (drained) uid starts a fresh lifecycle, as before
    engine.add_request(_req("a", seed=3))
    assert len(engine.run()["a"]) == 4
    # terminal but NOT yet drained: a fresh lifecycle would clobber
    # the result sitting in finished/statuses — also rejected
    engine.add_request(_req("a", seed=4))
    while engine.has_work:
        engine.step()
    assert "a" in engine.finished
    with pytest.raises(ValueError, match="awaiting drain"):
        engine.add_request(_req("a", seed=5))
    assert len(engine.run()["a"]) == 4     # the result survived


# ---------------------------------------------------------------------------
# bounded queue + backpressure
# ---------------------------------------------------------------------------


def test_queue_bound_raises_and_try_add_sheds(tiny_gpt):
    engine = _mk(tiny_gpt, max_waiting=2)
    engine.add_request(_req("r0", seed=0))
    engine.add_request(_req("r1", seed=1))
    with pytest.raises(QueueFullError, match="max_waiting"):
        engine.add_request(_req("r2", seed=2))
    assert engine.try_add(_req("r3", seed=3)) is False
    # the shed request was never touched: no status, no deadline entry
    assert engine.stats()["num_rejected_queue_full"] == 2
    assert engine.stats()["queue_depth"] == 2
    out = engine.run()
    assert set(out) == {"r0", "r1"}
    # the queue drained — the backpressure signal clears with it
    assert engine.try_add(_req("r2", seed=2)) is True
    assert engine.run()["r2"]
    # a drained request OBJECT re-submitted into a full queue is shed
    # with status None — never a stale verdict from its old lifecycle
    done = _req("old", seed=7)
    engine.add_request(done)
    engine.run()
    assert done.status == "finished"
    engine.add_request(_req("f0", seed=8))
    engine.add_request(_req("f1", seed=9))
    assert engine.try_add(done) is False
    assert done.status is None


def test_try_add_still_raises_on_caller_bugs(tiny_gpt):
    engine = _mk(tiny_gpt, max_waiting=4)
    engine.add_request(_req("a"))
    with pytest.raises(ValueError, match="already waiting"):
        engine.try_add(_req("a", seed=1))   # a bug, not load
    with pytest.raises(ValueError, match="priority"):
        engine.try_add(_req("b", priority=-1))


def test_queue_bound_config_validation():
    for bad in (dict(max_waiting=0), dict(queue_high_watermark=0),
                dict(free_block_low_watermark=0.0),
                dict(free_block_low_watermark=1.5),
                dict(degrade_patience=0),
                dict(degrade_admit_priority=0),
                # unreachable watermark: the queue never exceeds
                # max_waiting + max_batch, so the ladder's queue
                # signal would be silently inert
                dict(max_batch=2, max_waiting=4,
                     queue_high_watermark=20)):
        with pytest.raises(ValueError):
            EngineConfig(**bad)
    # reachable (inside the requeue overshoot) validates fine
    EngineConfig(max_batch=2, max_waiting=4, queue_high_watermark=6)


# ---------------------------------------------------------------------------
# priority admission + priority-aware preemption
# ---------------------------------------------------------------------------


def test_priority_classes_admit_in_priority_then_arrival_order(tiny_gpt):
    engine = _mk(tiny_gpt, max_batch=1)
    engine.add_request(_req("low", seed=0, priority=2))
    engine.add_request(_req("hi", seed=1, priority=0))
    engine.add_request(_req("mid", seed=2, priority=1))
    out = engine.run()
    # finish order == admission order (max_batch=1): most urgent class
    # first, FIFO within a class
    assert list(out) == ["hi", "mid", "low"]
    # uniform priorities: plain arrival FIFO, the pre-priority behavior
    engine2 = _mk(tiny_gpt, max_batch=1)
    for uid, seed in (("low", 0), ("hi", 1), ("mid", 2)):
        engine2.add_request(_req(uid, seed=seed))
    assert list(engine2.run()) == ["low", "hi", "mid"]


def test_outputs_are_invariant_to_priority_assignment(tiny_gpt):
    """Priorities reorder SCHEDULING only: sampling is arrival-keyed,
    so each request's tokens are identical under any priority mix —
    the PR 2/3 determinism certs extended to mixed-priority
    schedules."""
    def serve(priorities):
        engine = _mk(tiny_gpt, max_batch=2, num_blocks=16)
        for i, prio in enumerate(priorities):
            engine.add_request(Request(
                f"r{i}", list(np.random.RandomState(i).randint(1, 100, 5)),
                max_new_tokens=6, priority=prio,
                sampling=(SamplingParams() if i % 2 == 0 else
                          SamplingParams(temperature=0.8, top_k=12))))
        return engine.run()

    uniform = serve([0, 0, 0, 0])
    mixed = serve([2, 0, 1, 0])
    inverted = serve([0, 1, 2, 3])
    assert uniform == mixed == inverted


def test_preemption_evicts_lowest_class_even_when_older(tiny_gpt):
    """The victim rule is (lowest class, then youngest): a LOW-priority
    lane yields even though it is the OLDER resident — where the old
    youngest-first rule would have evicted the high-priority one — and
    the preempted request still finishes with exactly its reference
    tokens (resume determinism is priority-blind)."""
    reqs = [_req("low", seed=3, n=5, new=8, priority=1),
            _req("hi", seed=4, n=5, new=8, priority=0)]

    def serve(num_blocks):
        engine = _mk(tiny_gpt, num_blocks=num_blocks, max_seq_len=16)
        for r in reqs:     # add_request starts a fresh lifecycle
            engine.add_request(r)
        preempted_uid = None
        while engine.has_work:
            before = engine.stats()["num_preemptions"]
            engine.step()
            if (preempted_uid is None
                    and engine.stats()["num_preemptions"] > before):
                resident = {s.request.uid for s in engine.slots
                            if s is not None}
                preempted_uid = ({"low", "hi"} - resident).pop()
        out, engine.finished = dict(engine.finished), {}
        return out, preempted_uid, engine.stats()["num_preemptions"]

    roomy, _, n_roomy = serve(num_blocks=32)
    tight, victim, n_tight = serve(num_blocks=4)
    assert n_roomy == 0 and n_tight >= 1
    # "low" was admitted FIRST (older) yet yields: class beats age
    assert victim == "low"
    assert tight == roomy


# ---------------------------------------------------------------------------
# the admit-time feasibility gate
# ---------------------------------------------------------------------------


def test_feasibility_gate_sheds_infeasible_deadlines(tiny_gpt):
    now = [0.0]
    engine = _mk(tiny_gpt, clock=lambda: now[0])
    # seed the estimators as if dispatches were observed at 1s each:
    # an 8-token prompt (one chunk, which emits the first token) + 5
    # decode ticks estimates 6s
    engine._ewma_prefill_s = 1.0
    engine._ewma_decode_s = 1.0
    engine.add_request(_req("doomed", seed=0, n=8, new=6, deadline_s=3.0))
    engine.add_request(_req("fine", seed=1, n=8, new=6, deadline_s=20.0))
    out = engine.run(return_status=True)
    assert out["doomed"].status == "rejected"
    assert out["doomed"].tokens == []
    assert out["fine"].status == "finished"
    assert len(out["fine"].tokens) == 6
    s = engine.stats()
    assert s["num_rejected_infeasible"] == 1
    assert s["num_timeouts"] == 0         # shed BEFORE burning the TTL
    # the request object carries the verdict too
    assert engine.allocator.num_used == 0


def test_feasibility_gate_prices_prefills_first_token(tiny_gpt):
    """The final prefill chunk emits the first generated token, so a
    fresh request owes decode only max_new_tokens - 1 — the gate must
    not charge a phantom decode dispatch (max_new_tokens=1 is served
    by the prefill pass alone)."""
    now = [0.0]
    engine = _mk(tiny_gpt, clock=lambda: now[0])
    engine._ewma_prefill_s = 1.0
    engine._ewma_decode_s = 1.0
    # est = 1 chunk + 0 decode dispatches = 1.0 <= 1.5 (the old
    # full-budget pricing said 2.0 and shed it)
    engine.add_request(_req("one", seed=0, n=8, new=1, deadline_s=1.5))
    out = engine.run(return_status=True)
    assert out["one"].status == "finished"
    assert len(out["one"].tokens) == 1
    assert engine.stats()["num_rejected_infeasible"] == 0


def test_feasibility_gate_stays_open_without_observations(tiny_gpt):
    # no dispatch observed yet => no estimate => no shedding: the gate
    # never guesses (a fresh engine under a fake clock serves a
    # tight-deadline request instead of rejecting it blind)
    now = [0.0]
    engine = _mk(tiny_gpt, clock=lambda: now[0])
    engine.add_request(_req("tight", seed=0, n=8, new=4, deadline_s=0.5))
    out = engine.run(return_status=True)
    assert out["tight"].status == "finished"
    assert engine.stats()["num_rejected_infeasible"] == 0


def test_feasibility_gate_models_decode_amortization(tiny_gpt):
    """The estimator counts decode DISPATCHES (ceil(remaining / K)),
    not tokens: the same deadline that is infeasible at K=1 admits at
    K=4 — the gate understands the multi-step engine it guards."""
    def verdict(k):
        now = [0.0]
        engine = _mk(tiny_gpt, clock=lambda: now[0], decode_steps=k)
        engine._ewma_prefill_s = 1.0
        engine._ewma_decode_s = 1.0
        # the prefill chunk emits token 1, so decode owes 5:
        # est(K=1) = 1 + 5 = 6 > 3.5; est(K=4) = 1 + 2 = 3 <= 3.5
        engine.add_request(_req("r", seed=0, n=8, new=6, deadline_s=3.5))
        return engine.run(return_status=True)["r"].status

    assert verdict(1) == "rejected"
    assert verdict(4) == "finished"


def test_feasibility_gate_charges_no_chunk_for_cached_resume(tiny_gpt):
    """A resumed entry whose whole history is prefix-cached skips
    prefill entirely (_admit starts it decoding directly) — the gate
    must not charge it a phantom chunk, or it sheds a request that was
    guaranteed to finish in time. A FRESH fully-cached prompt still
    costs one chunk (the write-suppressed logits pass)."""
    engine = _mk(tiny_gpt)
    engine._ewma_prefill_s = 1.0
    engine._ewma_decode_s = 0.1
    assert engine._estimate_service_s(0, 3) == pytest.approx(1.3)
    assert engine._estimate_service_s(0, 3, skips_prefill=True) \
        == pytest.approx(0.3)
    # a real uncached tail always charges its chunks
    assert engine._estimate_service_s(5, 3, skips_prefill=True) \
        == pytest.approx(1.3)


def test_duplicate_uid_guard_survives_snapshot_restore(tiny_gpt):
    """The O(1) live-uid set behind the duplicate guard must be
    repopulated by restore(): a restored queue's uids are waiting."""
    engine = _mk(tiny_gpt, max_batch=1)
    engine.add_request(_req("a", new=6))
    engine.add_request(_req("b", seed=1, new=6))
    engine.step()
    restored = _mk(tiny_gpt, max_batch=1)
    restored.restore(engine.snapshot())
    for uid in ("a", "b"):
        with pytest.raises(ValueError,
                           match="already waiting or resident"):
            restored.add_request(_req(uid, seed=5))
    out = restored.run()
    assert set(out) == {"a", "b"}
    # drained => uids live again
    restored.add_request(_req("a", seed=6))
    restored.run()


def test_ewma_estimators_populate_from_real_dispatches(tiny_gpt):
    engine = _mk(tiny_gpt)
    engine.add_request(_req("a"))
    engine.run()
    s = engine.stats()
    assert s["ewma_prefill_dispatch_s"] > 0.0
    assert s["ewma_decode_dispatch_s"] > 0.0


def test_prefill_ewma_excludes_retry_backoff(tiny_gpt, monkeypatch):
    """Backoff sleeps between retry attempts are failure handling, not
    service time: one transient fault must not inflate the feasibility
    gate's contention-free estimate into over-shedding. The fake clock
    advances ONLY inside the backoff sleeper, so any nonzero EWMA here
    is backoff contamination."""
    from apex_tpu.utils import faults as faults_mod
    from apex_tpu.utils.faults import FaultPlan, FaultSpec

    now = [0.0]
    monkeypatch.setattr(faults_mod.time, "sleep",
                        lambda s: now.__setitem__(0, now[0] + s))
    model, params = tiny_gpt
    engine = InferenceEngine(
        model, params,
        EngineConfig(retry_backoff_s=0.5, **ENGINE_KW),
        clock=lambda: now[0],
        faults=FaultPlan([FaultSpec(site="prefill", kind="transient",
                                    at=(0,))]))
    engine.add_request(_req("a"))
    res = engine.run(return_status=True)
    assert res["a"].status == "finished"
    s = engine.stats()
    assert s["num_dispatch_retries"] == 1      # the fault really fired
    assert s["ewma_prefill_dispatch_s"] == 0.0


# ---------------------------------------------------------------------------
# the degradation ladder
# ---------------------------------------------------------------------------


def test_ladder_steps_down_under_pressure_and_recovers(tiny_gpt):
    engine = _mk(tiny_gpt, max_batch=1, queue_high_watermark=3,
                 degrade_patience=1)
    for i in range(5):
        engine.add_request(_req(f"r{i}", seed=i, new=2))
    peak = 0
    while engine.has_work:
        engine.step()
        peak = max(peak, engine.stats()["degradation_level"])
    assert peak >= 1
    s = engine.stats()
    assert s["num_degrade_steps_down"] >= 1
    # idle ticks are clear ticks: the ladder walks back to 0
    for _ in range(4):
        engine.step()
    s = engine.stats()
    assert s["degradation_level"] == 0
    assert s["num_degrade_steps_up"] == s["num_degrade_steps_down"]


def test_ladder_rung2_flushes_prefix_cache(tiny_gpt):
    engine = _mk(tiny_gpt, enable_prefix_caching=True,
                 queue_high_watermark=100, degrade_patience=50)
    engine.add_request(_req("a", seed=0, n=8))
    engine.run()
    assert engine.stats()["blocks_cached"] > 0
    engine._degradation_level = 2     # hold the rung (patience=50)
    engine.step()
    s = engine.stats()
    assert s["blocks_cached"] == 0
    assert s["num_degrade_flushed_blocks"] > 0
    assert s["num_cache_evictions"] > 0


def test_ladder_rung1_suspends_speculation_reversibly(tiny_gpt):
    """Rung 1 reuses the quarantine degrade path (empty draft plan ->
    the verify program runs as a single-token step, bit-identically for
    greedy) but is REVERSIBLE — and it never flips ``_drafter_ok``."""
    from apex_tpu.serving import Drafter

    model, params = tiny_gpt

    class _EchoDrafter(Drafter):
        # always proposes (repeat the last token) and is a pure
        # function of the history — guarantees draft traffic exists
        # for the suspension to visibly stop
        def propose(self, history, max_tokens):
            return [int(history[-1])] * max_tokens

    prompt = list(np.random.RandomState(5).randint(1, 100, 8))
    cfg = EngineConfig(max_batch=2, block_size=4, num_blocks=64,
                       max_prefill_len=8, max_seq_len=64, seed=7,
                       spec_tokens=4, queue_high_watermark=100,
                       degrade_patience=50)

    def mk():
        return InferenceEngine(model, params, cfg,
                               drafter=_EchoDrafter())

    ref_engine = mk()
    ref_engine.add_request(Request("r", prompt, max_new_tokens=8))
    ref = ref_engine.run()
    assert ref_engine.stats()["num_draft_tokens"] > 0

    engine = mk()
    engine._degradation_level = 1
    assert engine.stats()["speculation_active"] == 0
    engine.add_request(Request("r", prompt, max_new_tokens=8))
    out = engine.run()
    assert out == ref                      # greedy bit-identity
    assert engine.stats()["num_draft_tokens"] == 0   # really suspended
    assert engine._drafter_ok              # NOT quarantined
    engine._degradation_level = 0          # pressure cleared
    assert engine.stats()["speculation_active"] == 1
    engine.add_request(Request("r2", prompt, max_new_tokens=8))
    engine.run()
    assert engine.stats()["num_draft_tokens"] > 0    # speculating again


def test_ladder_rung3_pauses_lowest_class_but_work_conserves(tiny_gpt):
    engine = _mk(tiny_gpt, queue_high_watermark=100, degrade_patience=50)
    engine._degradation_level = 3
    engine.add_request(_req("lo", seed=0, priority=1))
    engine.add_request(_req("hi", seed=1, priority=0))
    engine.step()
    resident = {s.request.uid for s in engine.slots if s is not None}
    # both lanes are free, but the paused class stays queued
    assert resident == {"hi"}
    assert engine.stats()["admission_paused"] == 1
    assert engine.stats()["queue_depth"] == 1
    # once nothing more urgent exists, the idle engine serves what it
    # has (work conservation — no deadlock against the stall guard)
    out = engine.run()
    assert set(out) == {"hi", "lo"}


def test_warm_prefix_cache_is_not_pressure(tiny_gpt):
    """The free-block watermark measures ALLOCATABLE headroom (free +
    evictable): a warm prefix cache under light traffic parks most of
    the pool at refcount 0, and a bare free-list signal would read
    that healthy state as overload and sawtooth the ladder
    (degrade -> flush -> re-warm -> degrade) forever."""
    engine = _mk(tiny_gpt, num_blocks=16, enable_prefix_caching=True,
                 free_block_low_watermark=0.3, degrade_patience=1)
    # two sequential distinct prompts: while either is RESIDENT the
    # allocatable fraction stays above the watermark (no real
    # pressure), but their retained cache blocks leave the bare free
    # list below it afterwards
    for i in range(2):
        engine.add_request(_req(f"warm{i}", seed=i, n=24, new=2))
        engine.run()
    s = engine.stats()
    assert s["blocks_cached"] > 0
    # the cache holds most of the pool, the free list is below the
    # watermark — but every cached block is allocatable headroom
    assert (engine.allocator.num_free
            / engine.allocator.num_blocks) <= 0.3
    for _ in range(4):
        engine.step()
    s = engine.stats()
    assert s["degradation_level"] == 0
    assert s["num_degrade_steps_down"] == 0
    assert s["blocks_cached"] > 0              # cache NOT flushed


def test_gate_ewmas_ride_snapshot_restore(tiny_gpt):
    """The feasibility-gate estimators serialize with the ladder
    state: a restored gate must not reopen blind (admitting doomed
    tight-deadline requests) right when the requeued backlog is at its
    largest. Absent keys (older snapshots) leave the gate open."""
    engine = _mk(tiny_gpt)
    engine._ewma_prefill_s = 0.75
    engine._ewma_decode_s = 0.25
    snap = json.loads(json.dumps(engine.snapshot()))
    restored = _mk(tiny_gpt)
    restored.restore(snap)
    s = restored.stats()
    assert s["ewma_prefill_dispatch_s"] == pytest.approx(0.75)
    assert s["ewma_decode_dispatch_s"] == pytest.approx(0.25)
    # a pre-overload snapshot without the keys: gate stays open. A
    # genuinely older snapshot predates the embedded checksum too —
    # drop the seal, or the (correct) integrity check reads this
    # hand-edited record as corruption
    del snap["overload"]["ewma_prefill_s"]
    del snap["overload"]["ewma_decode_s"]
    del snap["checksum"]
    older = _mk(tiny_gpt)
    older.restore(snap)
    assert older._ewma_prefill_s is None
    assert older._ewma_decode_s is None


def test_restore_into_ladder_disabled_config_clears_rung(tiny_gpt):
    """The overload knobs are restorable-across (out of the config
    fingerprint, like the retry knobs) — but an engine with NO
    watermarks can never walk the ladder back up, so restoring a
    mid-degradation snapshot into it must clear the rung instead of
    suspending speculation / pausing admission forever."""
    engine = _mk(tiny_gpt, max_batch=1, queue_high_watermark=2,
                 degrade_patience=1)
    for i in range(4):
        engine.add_request(_req(f"r{i}", seed=i, new=3, priority=i % 2))
    while engine.has_work and engine.stats()["degradation_level"] < 1:
        engine.step()
    snap = engine.snapshot()
    assert snap["overload"]["degradation_level"] >= 1

    plain = _mk(tiny_gpt, max_batch=1)     # ladder off (the default)
    plain.restore(snap)
    s = plain.stats()
    assert s["degradation_level"] == 0
    assert s["admission_paused"] == 0
    plain.run()                            # and it drains cleanly


def test_ladder_state_serializes_through_snapshot_restore(tiny_gpt):
    engine = _mk(tiny_gpt, max_batch=1, queue_high_watermark=2,
                 degrade_patience=1)
    for i in range(4):
        engine.add_request(_req(f"r{i}", seed=i, new=3,
                                priority=i % 2))
    while engine.has_work and engine.stats()["degradation_level"] < 1:
        engine.step()
    assert engine.stats()["degradation_level"] >= 1
    snap = engine.snapshot()
    assert snap["overload"]["degradation_level"] >= 1
    # priorities round-trip on every serialized request
    by_uid = {r["uid"]: r["priority"] for r in snap["requests"]}
    for uid, prio in by_uid.items():
        assert prio == int(uid[1:]) % 2, uid

    restored = _mk(tiny_gpt, max_batch=1, queue_high_watermark=2,
                   degrade_patience=1)
    restored.restore(snap)
    s = restored.stats()
    assert s["degradation_level"] == snap["overload"]["degradation_level"]
    restored.run()   # and it still drains cleanly


def test_decode_ewma_excludes_caller_pauses(tiny_gpt):
    """The decode EWMA times the drain's device fetch only: a driver
    that pauses between step() calls (or an operator pausing before
    snapshot) must not inflate the feasibility gate's contention-free
    estimate with idle time. The fake clock advances only BETWEEN
    ticks, so any nonzero EWMA here is pause contamination."""
    now = [0.0]
    engine = _mk(tiny_gpt, clock=lambda: now[0])
    engine.add_request(_req("a", new=5))
    while engine.has_work:
        engine.step()
        now[0] += 0.4                      # caller-side pause per tick
    engine.run()
    s = engine.stats()
    assert s["num_decode_dispatches"] > 0
    assert s["ewma_decode_dispatch_s"] == 0.0


def test_queue_depth_peak_counts_preemption_requeues(tiny_gpt):
    """The peak metric exists to expose the requeue overshoot past
    max_waiting — it must sample AT the requeue, before admission can
    re-absorb the entry (with an otherwise-empty queue, preemption is
    the only thing that ever makes depth nonzero here)."""
    engine = _mk(tiny_gpt, num_blocks=4, max_seq_len=16)
    engine.add_request(_req("a", seed=3, n=5, new=8))
    engine.add_request(_req("b", seed=4, n=5, new=8))
    engine.run()
    s = engine.stats()
    assert s["num_preemptions"] >= 1
    # both fit the 2-lane engine up front, so the client-side peak is
    # 2 — anything above proves the requeue was sampled; at minimum
    # the preempted entry must register depth >= 1 post-admission
    assert s["queue_depth_peak"] >= 1


def test_waiting_queue_drops_drained_priority_classes(tiny_gpt):
    """Dead per-class deques must not accumulate: priority is an
    arbitrary client int, and a long-lived engine fed distinct values
    would otherwise scan (and hold) every class ever seen."""
    engine = _mk(tiny_gpt, max_batch=1)
    for i in range(4):
        engine.add_request(_req(f"r{i}", seed=i, new=2, priority=10 * i))
    engine.run()
    assert engine.waiting._classes == {}
    # expel (deadline sweep) drops drained classes too
    now = [0.0]
    engine2 = _mk(tiny_gpt, clock=lambda: now[0])
    engine2.add_request(_req("d", seed=0, priority=7, deadline_s=0.5))
    now[0] = 1.0
    engine2.step()
    assert engine2.waiting._classes == {}
    assert engine2.stats()["num_timeouts"] == 1


# ---------------------------------------------------------------------------
# queue observability
# ---------------------------------------------------------------------------


def test_stats_report_queue_depth_and_wait(tiny_gpt):
    now = [0.0]
    engine = _mk(tiny_gpt, max_batch=1, clock=lambda: now[0])
    for i in range(3):
        engine.add_request(_req(f"r{i}", seed=i, new=2))
    s = engine.stats()
    assert s["queue_depth"] == 3 and s["queue_depth_peak"] == 3
    while engine.has_work:
        now[0] += 1.0
        engine.step()
    s = engine.stats()
    assert s["queue_depth"] == 0
    assert s["queue_depth_peak"] == 3
    assert s["num_ticks"] >= 3
    # r1/r2 waited in the queue while r0 (admitted at wait 0) served
    assert s["queue_wait_max_ticks"] >= 1
    assert s["queue_wait_max_s"] >= s["queue_wait_mean_s"] > 0.0
    assert s["queue_wait_max_ticks"] >= s["queue_wait_mean_ticks"]
    for key in ("num_rejected_queue_full", "num_rejected_infeasible",
                "num_degrade_steps_down", "num_degrade_steps_up",
                "num_degrade_flushed_blocks", "admission_paused",
                "degradation_level"):
        assert key in s, key


# ---------------------------------------------------------------------------
# bench section smoke (CI satellite: the overload arm cannot rot)
# ---------------------------------------------------------------------------


def test_bench_serving_overload_section_smoke():
    """The overload bench arm (fast shape) must run end-to-end with
    zero stalls, a bounded queue, and finite latency percentiles — the
    BENCH_r01/r05 dead-section lesson applied to the new arm."""
    import importlib.util
    import pathlib

    path = pathlib.Path(__file__).resolve().parents[1] / "bench.py"
    spec = importlib.util.spec_from_file_location("_bench_overload", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rec = mod.bench_serving_overload(fast=True)
    assert rec["unit"] == "tokens/sec"
    assert rec["value"] > 0
    for key in ("p50_ttft_s", "p99_ttft_s", "p50_itl_s", "p99_itl_s",
                "goodput_tokens_per_sec", "decode_tokens_per_sec",
                "slo_attainment"):
        assert key in rec, key
        assert math.isfinite(rec[key]), key
    assert rec["p99_ttft_s"] >= rec["p50_ttft_s"] >= 0
    assert rec["num_stalls"] == 0
    assert rec["burst_factor"] == 4
    assert (rec["queue_depth_peak"]
            <= rec["max_waiting"] + rec["max_batch"])
    counts = rec["status_counts"]
    assert counts.get("finished", 0) > 0
    assert sum(counts.values()) == rec["num_requests_admitted"]
