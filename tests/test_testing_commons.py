"""apex_tpu.transformer.testing commons tier (reference:
apex/transformer/testing/commons.py (U) + NcclDistributedTestBase): the
harness must stand up/tear down model parallelism and run sharded fns,
and the toy modules must be trainable and TP-correct."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import (
    IdentityLayer,
    ToyParallelMLP,
    model_parallel_harness,
    set_random_seed,
)


def test_set_random_seed_deterministic():
    k1 = set_random_seed(7)
    a = np.random.randn(3)
    k2 = set_random_seed(7)
    b = np.random.randn(3)
    np.testing.assert_array_equal(a, b)
    assert jnp.array_equal(jax.random.key_data(k1), jax.random.key_data(k2))


def test_identity_layer_trains():
    layer = IdentityLayer(shape=(4, 4))
    params = layer.init(jax.random.PRNGKey(0))
    grads = jax.grad(lambda p: jnp.sum(layer.apply(p) ** 2))(params)
    w = params["params"]["weight"]
    np.testing.assert_allclose(np.asarray(grads["params"]["weight"]),
                               2 * np.asarray(w), rtol=1e-6)


def test_harness_runs_toy_mlp_and_tears_down():
    """The harness brings up tp=4, runs the Column->Row toy MLP sharded,
    matches the dense (tp=1) reference, and destroys the mesh on exit."""
    H, F, B = 8, 16, 4
    x = jnp.asarray(np.random.RandomState(0).randn(B, H), jnp.float32)
    model = ToyParallelMLP(hidden=H, ffn=F)

    with model_parallel_harness(tensor_model_parallel_size=4) as run:
        def init_and_apply(x):
            p = model.init(jax.random.PRNGKey(1), x)
            return model.apply(p, x)

        out_tp = run(init_and_apply, x, in_specs=P(), out_specs=P(),
                     check_vma=False)
        assert parallel_state.model_parallel_is_initialized()
    assert not parallel_state.model_parallel_is_initialized()

    with model_parallel_harness(tensor_model_parallel_size=1) as run:
        out_dense = run(init_and_apply, x, in_specs=P(), out_specs=P(),
                        check_vma=False)
    # TP layers init from the same master weight scheme at any tp, so
    # tp=4 and tp=1 agree numerically
    np.testing.assert_allclose(np.asarray(out_tp), np.asarray(out_dense),
                               rtol=2e-5, atol=2e-5)
