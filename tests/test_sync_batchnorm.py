"""SyncBatchNorm tests on the 8-device CPU mesh (upstream analog:
tests/distributed/synced_batchnorm/{single_gpu_unit_test,
two_gpu_unit_test,test_groups}.py, SURVEY.md §4): synced stats must equal
big-batch BatchNorm stats."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel import SyncBatchNorm, convert_syncbn_model


def _mesh():
    return jax.make_mesh((8,), ("data",))


def _x(seed=0, shape=(8, 4, 3, 6, 5)):
    # (devices, N, C, H, W) torch layout after sharding
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(*shape).astype("float32"))


def _reference_bn(xb, eps=1e-5):
    """Big-batch BN over (N, C, H, W) in numpy."""
    mean = xb.mean(axis=(0, 2, 3))
    var = xb.var(axis=(0, 2, 3))
    return (xb - mean[None, :, None, None]) / np.sqrt(var[None, :, None, None] + eps)


def test_syncbn_matches_bigbatch_bn():
    mesh = _mesh()
    x = _x()
    bn = SyncBatchNorm(num_features=3, axis_name="data")
    variables = bn.init(jax.random.PRNGKey(0), jnp.zeros((4, 3, 6, 5)),
                        use_running_average=False)

    def f(v, x):
        x = x.reshape(4, 3, 6, 5)  # local block
        y, updates = bn.apply(v, x, use_running_average=False,
                              mutable=["batch_stats"])
        return y[None], updates["batch_stats"]

    y, stats = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=(P(), P("data")),
                      out_specs=(P("data"), P()))
    )(variables, x)

    xb = np.asarray(x).reshape(32, 3, 6, 5)
    ref = _reference_bn(xb)
    got = np.asarray(y).reshape(32, 3, 6, 5)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)

    # running stats: momentum*batch (torch convention), unbiased var
    n = 32 * 6 * 5
    np.testing.assert_allclose(
        np.asarray(stats["mean"]), 0.1 * xb.mean(axis=(0, 2, 3)), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(stats["var"]),
        0.9 * 1.0 + 0.1 * xb.var(axis=(0, 2, 3)) * n / (n - 1),
        rtol=1e-4, atol=1e-4,
    )


def test_syncbn_channel_last():
    mesh = _mesh()
    x = _x(shape=(8, 4, 6, 5, 3))
    bn = SyncBatchNorm(num_features=3, axis_name="data", channel_last=True)
    variables = bn.init(jax.random.PRNGKey(0), jnp.zeros((4, 6, 5, 3)),
                        use_running_average=False)

    def f(v, x):
        x = x.reshape(4, 6, 5, 3)
        y, _ = bn.apply(v, x, use_running_average=False, mutable=["batch_stats"])
        return y[None]

    y = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=(P(), P("data")), out_specs=P("data"))
    )(variables, x)
    xb = np.asarray(x).reshape(32, 6, 5, 3).transpose(0, 3, 1, 2)
    ref = _reference_bn(xb).transpose(0, 2, 3, 1)
    np.testing.assert_allclose(np.asarray(y).reshape(32, 6, 5, 3), ref,
                               rtol=1e-4, atol=1e-4)


def test_syncbn_process_groups():
    """test_groups analog: two groups of 4 normalize independently."""
    mesh = _mesh()
    x = _x()
    groups = ((0, 1, 2, 3), (4, 5, 6, 7))
    bn = SyncBatchNorm(num_features=3, axis_name="data", process_group=groups)
    variables = bn.init(jax.random.PRNGKey(0), jnp.zeros((4, 3, 6, 5)),
                        use_running_average=False)

    def f(v, x):
        x = x.reshape(4, 3, 6, 5)
        y, _ = bn.apply(v, x, use_running_average=False, mutable=["batch_stats"])
        return y[None]

    y = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=(P(), P("data")), out_specs=P("data"))
    )(variables, x)
    got = np.asarray(y).reshape(8, 4, 3, 6, 5)
    lo = _reference_bn(np.asarray(x)[:4].reshape(16, 3, 6, 5)).reshape(4, 4, 3, 6, 5)
    hi = _reference_bn(np.asarray(x)[4:].reshape(16, 3, 6, 5)).reshape(4, 4, 3, 6, 5)
    np.testing.assert_allclose(got[:4], lo, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(got[4:], hi, rtol=1e-4, atol=1e-4)


def test_syncbn_eval_uses_running_stats():
    bn = SyncBatchNorm(num_features=3, axis_name=None)
    variables = bn.init(jax.random.PRNGKey(0), jnp.zeros((2, 3, 4, 4)),
                        use_running_average=False)
    variables = {
        "params": variables["params"],
        "batch_stats": {"mean": jnp.array([1.0, 2.0, 3.0]),
                        "var": jnp.array([4.0, 4.0, 4.0])},
    }
    x = jnp.ones((2, 3, 4, 4))
    y = bn.apply(variables, x, use_running_average=True)
    exp = (1.0 - np.array([1, 2, 3])) / np.sqrt(4 + 1e-5)
    np.testing.assert_allclose(np.asarray(y)[0, :, 0, 0], exp, rtol=1e-5)


def test_syncbn_affine_and_dtype():
    bn = SyncBatchNorm(num_features=4, axis_name=None)
    v = bn.init(jax.random.PRNGKey(0), jnp.zeros((2, 4, 3, 3), jnp.bfloat16),
                use_running_average=False)
    assert v["params"]["scale"].dtype == jnp.float32
    x = jnp.ones((2, 4, 3, 3), jnp.bfloat16)
    y, _ = bn.apply(v, x, use_running_average=False, mutable=["batch_stats"])
    assert y.dtype == jnp.bfloat16


def test_syncbn_no_sync_matches_local_bn():
    """axis_name=None degrades to plain BN."""
    bn = SyncBatchNorm(num_features=3, axis_name=None)
    x = _x(shape=(4, 3, 6, 5))
    v = bn.init(jax.random.PRNGKey(0), x, use_running_average=False)
    y, _ = bn.apply(v, x, use_running_average=False, mutable=["batch_stats"])
    ref = _reference_bn(np.asarray(x))
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-4)


def test_syncbn_wrong_channels_raises():
    bn = SyncBatchNorm(num_features=5, axis_name=None)
    with pytest.raises(ValueError):
        bn.init(jax.random.PRNGKey(0), jnp.zeros((2, 3, 4, 4)),
                use_running_average=False)


def test_convert_syncbn_model():
    class Wrapper(nn.Module):
        bn: nn.Module

        def __call__(self, x):
            return self.bn(x)

    m = Wrapper(bn=nn.BatchNorm(use_running_average=False))
    converted = convert_syncbn_model(m, axis_name="data")
    assert isinstance(converted.bn, SyncBatchNorm)
    assert converted.bn.axis_name == "data"
    assert converted.bn.channel_last  # flax BN is feature-last


def test_larc_scales_updates():
    from apex_tpu.optimizers import FusedSGD
    from apex_tpu.parallel import LARC

    params = {"big": jnp.full((16,), 100.0), "small": jnp.full((16,), 0.01)}
    grads = {"big": jnp.full((16,), 1.0), "small": jnp.full((16,), 1.0)}
    base = FusedSGD(lr=1.0, momentum=0.0, weight_decay=0.0)
    larc = LARC(base, trust_coefficient=0.001, clip=True)
    st = larc.init(params)
    p, _ = larc.step(grads, st, params)

    # big: adaptive_lr = 0.001*400/(4) = 0.1 -> scale 0.1 (clip at 1)
    big_norm = np.sqrt(16 * 100.0 ** 2)
    g_norm = 4.0
    scale_big = min(0.001 * big_norm / g_norm / 1.0, 1.0)
    np.testing.assert_allclose(
        np.asarray(p["big"]), 100.0 - scale_big * 1.0, rtol=1e-5
    )
    # small params get tiny adaptive lr -> nearly frozen
    assert abs(float(p["small"][0]) - 0.01) < 1e-4


def test_larc_folds_weight_decay_into_grad():
    from apex_tpu.optimizers import FusedSGD
    from apex_tpu.parallel import LARC

    params = {"w": jnp.full((4,), 2.0)}
    grads = {"w": jnp.full((4,), 0.5)}
    base = FusedSGD(lr=0.1, momentum=0.0, weight_decay=0.5)
    larc = LARC(base, trust_coefficient=0.02, clip=False)
    p, _ = larc.step(grads, larc.init(params), params)
    pn = np.sqrt(4 * 4.0)  # ||p|| = 4
    gn = np.sqrt(4 * 0.25)  # ||g|| = 1
    adaptive = 0.02 * pn / (gn + 0.5 * pn + 1e-8)
    # reference clip=False: g' = (g + wd*p) * adaptive_lr, inner optimizer
    # applies lr on top -> step = lr * adaptive * (g + wd*p)
    gprime = (0.5 + 0.5 * 2.0) * adaptive
    exp = 2.0 - 0.1 * gprime
    np.testing.assert_allclose(np.asarray(p["w"]), exp, rtol=1e-5)


def test_larc_zero_grad_param_is_untouched():
    """Reference: the wd fold-in and scaling happen only for params with
    nonzero p/g norms; a frozen (zero-grad) param receives NO decay."""
    from apex_tpu.optimizers import FusedSGD
    from apex_tpu.parallel import LARC

    params = {"w": jnp.full((4,), 2.0)}
    grads = {"w": jnp.zeros((4,))}
    base = FusedSGD(lr=0.1, momentum=0.0, weight_decay=0.5)
    larc = LARC(base, trust_coefficient=0.02, clip=False)
    p, _ = larc.step(grads, larc.init(params), params)
    np.testing.assert_allclose(np.asarray(p["w"]), 2.0, rtol=1e-6)


def test_converted_module_is_usable():
    """Review regression: the converter's output must actually apply."""
    m = convert_syncbn_model(nn.BatchNorm(use_running_average=False))
    x = jnp.asarray(np.random.RandomState(0).randn(6, 4, 3).astype("float32"))
    v = m.init(jax.random.PRNGKey(0), x)
    y, _ = m.apply(v, x, mutable=["batch_stats"])
    # feature-last normalization over (6,4) per channel
    ref = (np.asarray(x) - np.asarray(x).mean((0, 1))) / np.sqrt(
        np.asarray(x).var((0, 1)) + 1e-5
    )
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-4)


def test_convert_preserves_scale_bias_split():
    m = convert_syncbn_model(nn.BatchNorm(use_running_average=False,
                                          use_scale=False, use_bias=True))
    x = jnp.ones((4, 3))
    v = m.init(jax.random.PRNGKey(0), x)
    assert "scale" not in v["params"]
    assert "bias" in v["params"]


def test_no_track_running_stats_uses_batch_stats_at_eval():
    """torch semantics: track_running_stats=False always normalizes with
    batch statistics (review regression)."""
    bn = SyncBatchNorm(num_features=3, axis_name=None, track_running_stats=False)
    x = 5.0 * jnp.ones((2, 3, 4, 4)) + jnp.asarray(
        np.random.RandomState(0).randn(2, 3, 4, 4).astype("float32"))
    v = bn.init(jax.random.PRNGKey(0), x, use_running_average=False)
    assert "batch_stats" not in v  # no dead collection
    y = bn.apply(v, x, use_running_average=True)
    assert abs(float(jnp.mean(y))) < 1e-5  # normalized, not identity


def test_unbound_axis_warns_and_falls_back_local():
    bn = SyncBatchNorm(num_features=3, axis_name="data")
    x = jnp.asarray(np.random.RandomState(0).randn(4, 3, 5, 5).astype("float32"))
    v = bn.init(jax.random.PRNGKey(0), x, use_running_average=False)  # no warn at init
    import warnings as w

    with w.catch_warnings(record=True) as caught:
        w.simplefilter("always")
        y, _ = bn.apply(v, x, use_running_average=False, mutable=["batch_stats"])
        assert any("not bound" in str(c.message) for c in caught)
    ref = _reference_bn(np.asarray(x))
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-4)


def test_convert_syncbn_model_recurses_into_submodules():
    """Nested-but-reachable BatchNorm fields convert (the torch version
    walks the whole tree; our walk covers recursive dataclass fields)."""
    import flax.linen as nn

    from apex_tpu.parallel import SyncBatchNorm, convert_syncbn_model

    class Inner(nn.Module):
        bn: nn.Module = None

        def setup(self):
            pass

        def __call__(self, x):
            return self.bn(x)

    class Outer(nn.Module):
        inner: nn.Module = None

        def __call__(self, x):
            return self.inner(x)

    model = Outer(inner=Inner(bn=nn.BatchNorm(use_running_average=False)))
    converted = convert_syncbn_model(model)
    assert isinstance(converted.inner.bn, SyncBatchNorm)


def test_convert_syncbn_model_warns_on_no_conversion():
    import warnings

    import flax.linen as nn

    from apex_tpu.parallel import convert_syncbn_model

    class NoBN(nn.Module):
        @nn.compact
        def __call__(self, x):
            return x

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        convert_syncbn_model(NoBN())
        assert any("no nn.BatchNorm among" in str(x.message) for x in w)


def test_convert_syncbn_model_walks_containers():
    """BatchNorms inside list/tuple fields of submodules convert too."""
    import flax.linen as nn

    from apex_tpu.parallel import SyncBatchNorm, convert_syncbn_model

    class Layer(nn.Module):
        bn: nn.Module = None

        def __call__(self, x):
            return self.bn(x)

    class Net(nn.Module):
        layers: tuple = ()

        def __call__(self, x):
            for l in self.layers:
                x = l(x)
            return x

    model = Net(layers=(Layer(bn=nn.BatchNorm(use_running_average=False)),
                        Layer(bn=nn.BatchNorm(use_running_average=False))))
    converted = convert_syncbn_model(model)
    assert all(isinstance(l.bn, SyncBatchNorm) for l in converted.layers)
