"""GPT causal-LM tests: causality, loss shift, backend parity (flash vs
composed, ring/ulysses on the mesh), and a train smoke."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.models import GPTConfig, GPTLMHeadModel, lm_loss


def _ids(B, S, vocab=128, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randint(0, vocab, (B, S)))


@pytest.mark.slow
def test_causality():
    """Changing a future token must not affect earlier logits."""
    cfg = GPTConfig.tiny(dropout=0.0)
    model = GPTLMHeadModel(cfg)
    ids = _ids(1, 16)
    params = model.init(jax.random.PRNGKey(0), ids)
    base = model.apply(params, ids)
    ids2 = ids.at[0, 10].set((int(ids[0, 10]) + 1) % cfg.vocab_size)
    mod = model.apply(params, ids2)
    np.testing.assert_allclose(np.asarray(base[0, :10]),
                               np.asarray(mod[0, :10]), rtol=1e-5, atol=1e-6)
    assert float(jnp.max(jnp.abs(base[0, 10:] - mod[0, 10:]))) > 1e-4


@pytest.mark.slow
def test_flash_matches_composed():
    kw = dict(dropout=0.0)
    m1 = GPTLMHeadModel(GPTConfig.tiny(fused_kernels=True, **kw))
    m2 = GPTLMHeadModel(GPTConfig.tiny(fused_kernels=False, **kw))
    ids = _ids(2, 32)
    params = m1.init(jax.random.PRNGKey(0), ids)
    a = m1.apply(params, ids)
    b = m2.apply(params, ids)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=2e-3, atol=2e-4)


@pytest.mark.parametrize("backend", ["ring", "ulysses"])
@pytest.mark.slow
def test_context_parallel_matches_single_device(backend):
    """Sequence-sharded GPT over the 8-device context mesh == the same
    model run unsharded."""
    cfg_cp = GPTConfig.tiny(dropout=0.0, attention_backend=backend,
                            num_heads=8)
    cfg_1 = GPTConfig.tiny(dropout=0.0, num_heads=8)
    m_cp = GPTLMHeadModel(cfg_cp)
    m_1 = GPTLMHeadModel(cfg_1)
    B, S = 2, 64
    ids = _ids(B, S)
    mesh = jax.make_mesh((8,), ("context",))
    params = m_1.init(jax.random.PRNGKey(0), ids)

    def f(params, ids_local):
        return m_cp.apply(params, ids_local)

    out_cp = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=(P(), P(None, "context")),
        out_specs=P(None, "context")))(params, ids)
    out_1 = m_1.apply(params, ids)
    np.testing.assert_allclose(np.asarray(out_cp), np.asarray(out_1),
                               rtol=2e-3, atol=2e-4)


def test_lm_loss_shift_and_ignore():
    logits = jnp.zeros((1, 4, 8))
    labels = jnp.asarray([[1, 2, -1, 3]])
    # uniform logits: per-token loss = log(8); positions 1 and 3 count
    # (position 2's label is ignore), position 0 is never a target
    loss = lm_loss(logits, labels)
    np.testing.assert_allclose(float(loss), np.log(8.0), rtol=1e-6)


@pytest.mark.slow
def test_train_smoke_with_fused_optimizer():
    from apex_tpu.optimizers import FusedAdam

    cfg = GPTConfig.tiny(dropout=0.0, remat=False)
    model = GPTLMHeadModel(cfg)
    ids = _ids(4, 24)
    params = model.init(jax.random.PRNGKey(0), ids)
    opt = FusedAdam(lr=1e-2)
    state = opt.init(params)

    @jax.jit
    def step(params, state):
        loss, grads = jax.value_and_grad(
            lambda p: lm_loss(model.apply(p, ids), ids))(params)
        params, state = opt.step(grads, state, params)
        return params, state, loss

    losses = []
    for _ in range(10):
        params, state, loss = step(params, state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9


def test_position_table_overflow_raises():
    cfg = GPTConfig.tiny(dropout=0.0, max_position_embeddings=16)
    model = GPTLMHeadModel(cfg)
    ids = _ids(1, 32)  # 32 > 16
    with pytest.raises(ValueError):
        model.init(jax.random.PRNGKey(0), ids)

    cfg_cp = GPTConfig.tiny(dropout=0.0, attention_backend="ring",
                            num_heads=8, max_position_embeddings=16)
    m_cp = GPTLMHeadModel(cfg_cp)
    mesh = jax.make_mesh((8,), ("context",))
    ids8 = _ids(1, 64)  # 8 shards x 8 = 64 global > 16

    def f(ids_local):
        return m_cp.init(jax.random.PRNGKey(0), ids_local)

    with pytest.raises(ValueError):
        jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P(None, "context"),
                              out_specs=P()))(ids8)


@pytest.mark.slow
def test_gpt_trains_with_dropout_active():
    """Training-mode dropout paths (fused attention-prob dropout +
    fused hidden dropout) produce finite loss/grads and differ run-to-
    run with different dropout keys; the threefry fallback
    (fused_kernels=False) also runs."""
    from apex_tpu.models.gpt import GPTConfig, GPTLMHeadModel, lm_loss

    for fused in (True, False):
        cfg = GPTConfig.tiny(dropout=0.1, fused_kernels=fused)
        model = GPTLMHeadModel(cfg)
        ids = jnp.asarray(np.random.RandomState(0).randint(
            0, cfg.vocab_size, (2, 32)))
        params = model.init(jax.random.PRNGKey(0), ids)["params"]

        def loss_fn(p, key):
            logits = model.apply({"params": p}, ids, deterministic=False,
                                 rngs={"dropout": key})
            return lm_loss(logits, ids)

        loss, g = jax.jit(jax.value_and_grad(loss_fn))(
            params, jax.random.PRNGKey(1))
        assert np.isfinite(float(loss))
        assert all(np.isfinite(np.asarray(l)).all()
                   for l in jax.tree.leaves(g))
        loss2 = jax.jit(loss_fn)(params, jax.random.PRNGKey(2))
        assert float(loss) != float(loss2)  # new key -> new masks


@pytest.mark.slow
def test_gpt_ring_backend_trains_with_attention_dropout():
    """The ring backend trains at the TRUE dropout config (round-3
    verdict missing #1, closed round 4): attention-probability dropout
    is fused per block and actually perturbs the output — eval and
    train passes differ, and the train pass is deterministic in the
    rng (backward-replayable)."""
    from apex_tpu.models.gpt import GPTConfig, GPTLMHeadModel

    mesh = jax.make_mesh((2,), ("context",))
    cfg = GPTConfig.tiny(dropout=0.5, attention_backend="ring")
    model = GPTLMHeadModel(cfg)
    ids = jnp.arange(16, dtype=jnp.int32)[None]  # (1, 16)
    from jax.sharding import PartitionSpec as P

    def f(ids, det):
        params = model.init(jax.random.PRNGKey(0), ids)["params"]
        out = model.apply({"params": params}, ids, deterministic=det,
                          rngs={"dropout": jax.random.PRNGKey(1)})
        return out.astype(jnp.float32)

    def run(det):
        return jax.jit(jax.shard_map(
            functools.partial(f, det=det), mesh=mesh,
            in_specs=P(None, "context"),
            out_specs=P(None, "context")))(ids)

    train1, train2, evald = run(False), run(False), run(True)
    # dropout active: train != eval; deterministic in the rng
    assert not np.allclose(np.asarray(train1), np.asarray(evald))
    np.testing.assert_allclose(np.asarray(train1), np.asarray(train2),
                               rtol=1e-6, atol=1e-6)
