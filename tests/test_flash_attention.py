"""Flash attention parity vs the composed-softmax reference (pattern:
the reference's fused-vs-composed kernel tests, SURVEY.md §4; component:
contrib fmha / fast_multihead_attn)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# Interpret-mode Pallas kernels on CPU are the suite's dominant cost
# (~5 min for this tier alone); fast CI runs -m "not slow", the full
# run and the on-TPU tier keep the coverage.
pytestmark = pytest.mark.slow

from apex_tpu.ops.flash_attention import flash_attention, mha_reference


def _mk(B, H, Sq, Sk, D, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (jax.random.normal(ks[0], (B, H, Sq, D), dtype),
            jax.random.normal(ks[1], (B, H, Sk, D), dtype),
            jax.random.normal(ks[2], (B, H, Sk, D), dtype))


def _max_err(a, b):
    return float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))


@pytest.mark.parametrize("shape,causal,use_mask", [
    ((2, 4, 128, 64), False, False),
    ((2, 4, 128, 64), False, True),
    ((1, 2, 256, 64), True, False),
    ((2, 2, 100, 64), False, True),      # unaligned seq
    ((1, 1, 37, 32), True, False),       # unaligned seq + head dim
    ((1, 2, 640, 64), False, True),      # multi-block online softmax
])
def test_parity_fwd_bwd(shape, causal, use_mask):
    B, H, S, D = shape
    q, k, v = _mk(B, H, S, S, D)
    km = ((jax.random.uniform(jax.random.PRNGKey(9), (B, S)) < 0.3)
          if use_mask else None)
    scale = 1.0 / np.sqrt(D)

    out = jax.jit(lambda q, k, v: flash_attention(q, k, v, km, causal, scale))(
        q, k, v)
    ref = mha_reference(q, k, v, km, causal, scale)
    assert _max_err(out, ref) < 2e-5

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, km, causal, scale) * 1.3)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, km, causal, scale) * 1.3)

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g, gr):
        assert _max_err(a, b) < 3e-4


@pytest.mark.parametrize("S", [128, 100, 37])
def test_fully_masked_rows_are_finite(S):
    """All keys masked -> uniform distribution (finite), matching the
    reference's -30000 fill semantics, not NaN. Unaligned S regression:
    wrapper-padded keys must NOT count toward the uniform denominator
    (an Sk=100 row block pads to 128; the old code returned outputs
    scaled by 100/128)."""
    q, k, v = _mk(1, 1, S, S, 64)
    km = jnp.ones((1, S), bool)
    out = flash_attention(q, k, v, km, False, 0.125)
    assert bool(jnp.all(jnp.isfinite(out)))
    ref = mha_reference(q, k, v, km, False, 0.125)
    assert _max_err(out, ref) < 2e-5


def test_bf16_io_fp32_accumulation():
    q, k, v = _mk(2, 2, 256, 256, 64, jnp.bfloat16)
    out = flash_attention(q, k, v, None, False, 0.125)
    assert out.dtype == jnp.bfloat16
    ref = mha_reference(q.astype(jnp.float32), k.astype(jnp.float32),
                        v.astype(jnp.float32), None, False, 0.125)
    assert _max_err(out, ref) < 0.02


def test_bert_model_flash_matches_composed():
    """Model-level: BertModel with the flash path forced on vs off."""
    from apex_tpu.models import BertConfig, BertForPreTraining

    rng = np.random.RandomState(0)
    B, S = 2, 64
    kw = dict(hidden_dropout=0.0, attention_dropout=0.0,
              max_position_embeddings=S, num_layers=2)
    cfg_flash = BertConfig.tiny(flash_min_seq=1, **kw)
    cfg_comp = BertConfig.tiny(flash_attention=False, **kw)

    ids = jnp.asarray(rng.randint(0, cfg_flash.vocab_size, (B, S)))
    types = jnp.zeros((B, S), jnp.int32)
    attn = jnp.asarray((rng.rand(B, S) > 0.2).astype(np.int32))

    m1 = BertForPreTraining(cfg_flash)
    m2 = BertForPreTraining(cfg_comp)
    params = m1.init(jax.random.PRNGKey(0), ids, types, attn)["params"]

    mlm1, nsp1 = m1.apply({"params": params}, ids, types, attn)
    mlm2, nsp2 = m2.apply({"params": params}, ids, types, attn)
    assert _max_err(mlm1, mlm2) < 5e-4
    assert _max_err(nsp1, nsp2) < 5e-4

    def loss1(p):
        a, b = m1.apply({"params": p}, ids, types, attn)
        return jnp.sum(a.astype(jnp.float32)) * 1e-3 + jnp.sum(b)

    def loss2(p):
        a, b = m2.apply({"params": p}, ids, types, attn)
        return jnp.sum(a.astype(jnp.float32)) * 1e-3 + jnp.sum(b)

    g1 = jax.grad(loss1)(params)
    g2 = jax.grad(loss2)(params)
    errs = jax.tree.map(_max_err, g1, g2)
    assert max(jax.tree.leaves(errs)) < 5e-3


def test_flash_attention_with_lse_fwd_bwd():
    """(out, lse) variant: lse matches composed logsumexp, and grads are
    correct INCLUDING a live lse cotangent (the ring-merge consumer)."""
    from apex_tpu.ops.flash_attention import (
        _with_lse_reference,
        flash_attention_with_lse,
    )

    q, k, v = _mk(1, 2, 100, 100, 64, seed=5)
    out, lse = flash_attention_with_lse(q, k, v, None, True, 0.125)
    ref_out, ref_lse = _with_lse_reference(q, k, v, None, True, 0.125)
    assert lse.shape == (1, 2, 1, 100)
    assert _max_err(out, ref_out) < 2e-5
    assert _max_err(lse, ref_lse) < 2e-5

    def loss_k(q, k, v):
        o, l = flash_attention_with_lse(q, k, v, None, True, 0.125)
        return jnp.sum(jnp.sin(o)) + jnp.sum(jnp.cos(l))

    def loss_r(q, k, v):
        o, l = _with_lse_reference(q, k, v, None, True, 0.125)
        return jnp.sum(jnp.sin(o)) + jnp.sum(jnp.cos(l))

    gk = jax.jit(jax.grad(loss_k, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gk, gr):
        assert _max_err(a, b) < 3e-4


# ------------------------------------------------------------- dropout

def test_dropout_parity_with_extracted_mask():
    """Fused dropout == composed attention using the kernel's OWN
    keep-mask (flash_dropout_keep_mask reproduces the in-kernel bits
    exactly on either backend), fwd and bwd."""
    from apex_tpu.ops.flash_attention import (
        flash_dropout_keep_mask,
        mha_with_mask_reference,
    )

    B, H, S, D = 2, 3, 128, 64
    rate, seed = 0.1, 1234
    q, k, v = _mk(B, H, S, S, D)
    km = jax.random.uniform(jax.random.PRNGKey(9), (B, S)) < 0.2
    scale = 1.0 / np.sqrt(D)

    out = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, km, False, scale, rate, seed))(q, k, v)
    keep = flash_dropout_keep_mask(B, H, S, S, rate, seed)
    ref = mha_with_mask_reference(q, k, v, keep, km, False, scale, rate)
    assert _max_err(out, ref) < 2e-5

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, km, False, scale,
                                       rate, seed) * 1.3)

    def loss_ref(q, k, v):
        return jnp.sum(mha_with_mask_reference(q, k, v, keep, km, False,
                                               scale, rate) * 1.3)

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g, gr):
        assert _max_err(a, b) < 3e-4


def test_dropout_parity_unaligned_multiblock():
    """Dropout mask replay across tile boundaries: unaligned S forces
    padding, S=640 forces the multi-block online-softmax recurrence."""
    from apex_tpu.ops.flash_attention import (
        flash_dropout_keep_mask,
        mha_with_mask_reference,
    )

    for (S, causal) in [(100, False), (640, True)]:
        B, H, D = 1, 2, 64
        rate, seed = 0.15, 77
        q, k, v = _mk(B, H, S, S, D, seed=3)
        scale = 1.0 / np.sqrt(D)
        out = jax.jit(lambda q, k, v: flash_attention(
            q, k, v, None, causal, scale, rate, seed))(q, k, v)
        keep = flash_dropout_keep_mask(B, H, S, S, rate, seed)
        ref = mha_with_mask_reference(q, k, v, keep, None, causal, scale,
                                      rate)
        assert _max_err(out, ref) < 2e-5

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, None, causal, scale,
                                           rate, seed))

        def loss_ref(q, k, v):
            return jnp.sum(mha_with_mask_reference(q, k, v, keep, None,
                                                   causal, scale, rate))

        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
        for a, b in zip(g, gr):
            assert _max_err(a, b) < 3e-4


def test_dropout_mask_statistics_and_seed_sensitivity():
    """Keep-rate ~= 1-rate; different seeds give different masks; the
    same seed is deterministic."""
    from apex_tpu.ops.flash_attention import flash_dropout_keep_mask

    B, H, S = 2, 4, 256
    rate = 0.1
    m1 = np.asarray(flash_dropout_keep_mask(B, H, S, S, rate, 5))
    m2 = np.asarray(flash_dropout_keep_mask(B, H, S, S, rate, 5))
    m3 = np.asarray(flash_dropout_keep_mask(B, H, S, S, rate, 6))
    assert (m1 == m2).all()
    assert (m1 != m3).any()
    keep_frac = m1.mean()
    assert abs(keep_frac - (1 - rate)) < 0.01


def test_dropout_zero_rate_matches_no_dropout():
    B, H, S, D = 1, 2, 128, 64
    q, k, v = _mk(B, H, S, S, D)
    a = flash_attention(q, k, v, None, False, 0.125)
    b = flash_attention(q, k, v, None, False, 0.125, 0.0, 3)
    assert _max_err(a, b) == 0.0


def test_dropout_requires_seed():
    B, H, S, D = 1, 1, 128, 64
    q, k, v = _mk(B, H, S, S, D)
    with pytest.raises(ValueError, match="dropout_seed"):
        jax.jit(lambda q, k, v: flash_attention(
            q, k, v, None, False, 1.0, 0.1, None))(q, k, v)


# ------------------------------------------------ (B, S, NH*D) bsh entry

def _bsh_ref(q, k, v, NH, causal, scale, rate=0.0, seed=None, km=None):
    """Transposed-entry reference for the flat layout."""
    from apex_tpu.ops.flash_attention import flash_attention

    B, S, H = q.shape
    D = H // NH

    def split(t):
        return t.reshape(B, S, NH, D).transpose(0, 2, 1, 3)

    out = flash_attention(split(q), split(k), split(v), km, causal, scale,
                          rate, seed)
    return out.transpose(0, 2, 1, 3).reshape(B, S, H)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("rate,seed", [(0.0, None), (0.1, 42)])
def test_bsh_entry_matches_transposed(causal, rate, seed):
    """flash_attention_bsh (head-group kernels on flat activations) is
    bitwise the transposed entry — outputs AND gradients, with and
    without fused dropout (identical per-head PRNG tile ids)."""
    from apex_tpu.ops.flash_attention import flash_attention_bsh

    B, S, NH, D = 2, 128, 4, 64
    H = NH * D
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H), jnp.float32) for kk in ks)
    out = flash_attention_bsh(q, k, v, None, NH, causal, 0.125, rate, seed)
    ref = _bsh_ref(q, k, v, NH, causal, 0.125, rate, seed)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def loss(f):
        return lambda q, k, v: jnp.sum(jnp.sin(f(q, k, v)))

    g = jax.grad(loss(lambda a, b, c: flash_attention_bsh(
        a, b, c, None, NH, causal, 0.125, rate, seed)), argnums=(0, 1, 2))(
        q, k, v)
    gr = jax.grad(loss(lambda a, b, c: _bsh_ref(
        a, b, c, NH, causal, 0.125, rate, seed)), argnums=(0, 1, 2))(
        q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_bsh_entry_unaligned_seq_and_mask():
    from apex_tpu.ops.flash_attention import flash_attention_bsh

    B, S, NH, D = 2, 100, 4, 64  # S pads 100 -> 128 in-entry
    H = NH * D
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H), jnp.float32) for kk in ks)
    km = jnp.asarray(np.random.RandomState(2).rand(B, S) < 0.2)
    out = flash_attention_bsh(q, k, v, km, NH, False, 0.125)
    ref = _bsh_ref(q, k, v, NH, False, 0.125, km=km)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_bsh_entry_fallback_paths():
    """Configs the head-group kernels can't take (odd NH at D=64, or a
    multi-tile sequence) must transparently fall back to the transposed
    entry with identical semantics."""
    from apex_tpu.ops.flash_attention import flash_attention_bsh

    # odd NH=3 at D=64: no valid 128-lane grouping
    B, S, NH, D = 1, 128, 3, 64
    H = NH * D
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H), jnp.float32) for kk in ks)
    out = flash_attention_bsh(q, k, v, None, NH, False, 0.125)
    ref = _bsh_ref(q, k, v, NH, False, 0.125)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)

    # S=640: beyond the single-tile regime
    B, S, NH, D = 1, 640, 4, 64
    H = NH * D
    ks = jax.random.split(jax.random.PRNGKey(4), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H), jnp.float32) for kk in ks)
    out = flash_attention_bsh(q, k, v, None, NH, True, 0.125, 0.1, 7)
    ref = _bsh_ref(q, k, v, NH, True, 0.125, 0.1, 7)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)
