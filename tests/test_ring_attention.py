"""Ring attention (context parallelism) tests on the 8-device CPU mesh:
sharded ring == full-sequence attention, forward and gradients
(SURVEY.md §5 long-context stretch)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

# Interpret-mode Pallas kernels on CPU are the suite's dominant cost
# (~5 min for this tier alone); fast CI runs -m "not slow", the full
# run and the on-TPU tier keep the coverage.
pytestmark = pytest.mark.slow

from apex_tpu.ops.ring_attention import (
    ring_attention,
    ring_attention_reference,
)

CP = 8
B, H, D = 2, 2, 16
S = 64  # global sequence; 8 tokens per device


def _qkv(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (jax.random.normal(ks[0], (B, H, S, D)),
            jax.random.normal(ks[1], (B, H, S, D)),
            jax.random.normal(ks[2], (B, H, S, D)))


def _run_ring(q, k, v, key_mask=None, causal=False, scale=0.25):
    mesh = jax.make_mesh((CP,), ("context",))

    def f(q, k, v, km):
        return ring_attention(q, k, v, km, causal, scale,
                              axis_name="context")

    km = (jnp.zeros((B, S), bool) if key_mask is None else key_mask)
    return jax.jit(jax.shard_map(
        f, mesh=mesh,
        in_specs=(P(None, None, "context"), P(None, None, "context"),
                  P(None, None, "context"), P(None, "context")),
        out_specs=P(None, None, "context")))(q, k, v, km)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full_attention(causal):
    q, k, v = _qkv()
    out = _run_ring(q, k, v, causal=causal)
    ref = ring_attention_reference(q, k, v, None, causal, 0.25)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ring_with_padding_mask():
    q, k, v = _qkv(1)
    km = jnp.asarray(np.random.RandomState(2).rand(B, S) < 0.25)
    out = _run_ring(q, k, v, key_mask=km)
    ref = ring_attention_reference(q, k, v, km, False, 0.25)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_gradients_match_full(causal):
    q, k, v = _qkv(3)
    mesh = jax.make_mesh((CP,), ("context",))
    km = jnp.zeros((B, S), bool)

    def ring_loss(q, k, v, km):
        out = ring_attention(q, k, v, km, causal, 0.25,
                             axis_name="context")
        return jax.lax.psum(jnp.sum(jnp.sin(out.astype(jnp.float32))),
                            "context")

    g = jax.jit(jax.shard_map(
        jax.grad(ring_loss, argnums=(0, 1, 2)), mesh=mesh,
        in_specs=(P(None, None, "context"), P(None, None, "context"),
                  P(None, None, "context"), P(None, "context")),
        out_specs=(P(None, None, "context"), P(None, None, "context"),
                   P(None, None, "context"))))(q, k, v, km)

    def ref_loss(q, k, v):
        out = ring_attention_reference(q, k, v, None, causal, 0.25)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def _assemble_ring_keep_mask(dropout_rate, seed, causal=False):
    """The full (B, H, S, S) keep-mask the ring path applies: per
    (q-block, kv-block) pair, the flash keep-mask at that block's hashed
    seed — the exact bits the per-block kernels (or their bit-matched
    CPU fallback) draw."""
    from apex_tpu.ops.flash_attention import flash_dropout_keep_mask
    from apex_tpu.ops.ring_attention import _block_seed

    s_loc = S // CP
    keep = np.zeros((B, H, S, S), bool)
    for qb in range(CP):
        for kb in range(CP):
            if causal and kb > qb:
                continue  # skipped block: no bits drawn, contribution 0
            seed_bk = _block_seed(seed, jnp.int32(qb), jnp.int32(kb), CP)
            blk = flash_dropout_keep_mask(B, H, s_loc, s_loc, dropout_rate,
                                          seed_bk)
            keep[:, :, qb * s_loc:(qb + 1) * s_loc,
                 kb * s_loc:(kb + 1) * s_loc] = np.asarray(blk)
    return jnp.asarray(keep)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_fused_dropout_matches_composed(causal):
    """Ring attention at dropout 0.1 == composed dropout(softmax) @ v
    with the SAME per-block keep-masks — the lse-merge linearity
    argument, verified bit-matched (the round-3 verdict's missing #1)."""
    from apex_tpu.ops.flash_attention import mha_with_mask_reference

    q, k, v = _qkv(7)
    rate, seed = 0.1, 1234
    mesh = jax.make_mesh((CP,), ("context",))

    def f(q, k, v, km):
        return ring_attention(q, k, v, km, causal, 0.25,
                              axis_name="context", dropout_rate=rate,
                              dropout_seed=seed)

    km = jnp.zeros((B, S), bool)
    out = jax.jit(jax.shard_map(
        f, mesh=mesh,
        in_specs=(P(None, None, "context"), P(None, None, "context"),
                  P(None, None, "context"), P(None, "context")),
        out_specs=P(None, None, "context")))(q, k, v, km)

    keep = _assemble_ring_keep_mask(rate, seed, causal)
    ref = mha_with_mask_reference(q, k, v, keep, None, causal, 0.25, rate)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)
    # dropout actually dropped something (mask is non-trivial)
    assert not bool(keep.all())


def test_ring_dropout_gradients_match_composed():
    """Gradients through the ring's dropout path == autodiff of the
    composed form with the identical assembled keep-mask (backward
    replays the same per-block masks on the reverse ring pass)."""
    from apex_tpu.ops.flash_attention import mha_with_mask_reference

    q, k, v = _qkv(8)
    rate, seed = 0.15, 99
    mesh = jax.make_mesh((CP,), ("context",))
    km = jnp.zeros((B, S), bool)

    def ring_loss(q, k, v, km):
        out = ring_attention(q, k, v, km, False, 0.25,
                             axis_name="context", dropout_rate=rate,
                             dropout_seed=seed)
        return jax.lax.psum(jnp.sum(jnp.sin(out.astype(jnp.float32))),
                            "context")

    g = jax.jit(jax.shard_map(
        jax.grad(ring_loss, argnums=(0, 1, 2)), mesh=mesh,
        in_specs=(P(None, None, "context"), P(None, None, "context"),
                  P(None, None, "context"), P(None, "context")),
        out_specs=(P(None, None, "context"), P(None, None, "context"),
                   P(None, None, "context"))))(q, k, v, km)

    keep = _assemble_ring_keep_mask(rate, seed, False)

    def ref_loss(q, k, v):
        out = mha_with_mask_reference(q, k, v, keep, None, False, 0.25,
                                      rate)
        return jnp.sum(jnp.sin(out.astype(jnp.float32)))

    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_ring_memory_is_blockwise():
    """The defining property: no device ever sees more than one
    (S/cp)-block of keys at a time — checked structurally by running a
    sequence whose FULL score matrix would be big while per-step blocks
    are tiny (smoke: it executes; the parity tests prove correctness)."""
    S_big = 256
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (1, 1, S_big, D))
    k = jax.random.normal(ks[1], (1, 1, S_big, D))
    v = jax.random.normal(ks[2], (1, 1, S_big, D))
    out = _run_ring(q[:, :, :S_big], k, v, key_mask=jnp.zeros((1, S_big),
                                                              bool))
    assert out.shape == (1, 1, S_big, D)
    assert np.isfinite(np.asarray(out)).all()
