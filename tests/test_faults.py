"""Chaos certification (tier-1, CPU): the robustness layer of ISSUE 6.

Every failure path — transient dispatch errors, poison-request
quarantine, request deadlines, simulated process death, non-finite-loss
escalation — is driven by a seeded deterministic
:class:`~apex_tpu.utils.faults.FaultPlan`, and the recovery paths are
held to the bit-identity bar PRs 2-4 set: a snapshot/restored engine's
outputs and a checkpoint/resumed train run's final params must equal
the fault-free run exactly. All failure-path counters are asserted
nonzero where their path fires."""

import json
import math
import os
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flax.linen as nn

from apex_tpu.models import GPTConfig, GPTLMHeadModel
from apex_tpu.optimizers import FusedAdam
from apex_tpu.serving import (
    EngineConfig,
    EngineStalledError,
    InferenceEngine,
    Request,
    RequestResult,
    SamplingParams,
)
from apex_tpu.train import (
    NonFiniteLossError,
    TrainLoop,
    WatchdogConfig,
    build_train_step,
)
from apex_tpu.utils.checkpoint import load_train_state
from apex_tpu.utils.faults import (
    DispatchFailedError,
    FaultPlan,
    FaultSpec,
    SimulatedCrash,
    TransientDispatchError,
    nan_corrupt,
)

# ---------------------------------------------------------------------------
# fixtures: one tiny GPT + a standard two-request workload
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_gpt():
    cfg = GPTConfig.tiny(dropout=0.0, remat=False)
    model = GPTLMHeadModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    return model, params


ENGINE_KW = dict(max_batch=2, block_size=4, num_blocks=32,
                 max_prefill_len=8, max_seq_len=32,
                 enable_prefix_caching=True, seed=7)


def _mk_engine(tiny_gpt, faults=None, clock=None, **overrides):
    model, params = tiny_gpt
    kw = dict(ENGINE_KW)
    kw.update(overrides)
    return InferenceEngine(model, params, EngineConfig(**kw),
                           faults=faults, clock=clock)


def _requests():
    # one greedy, one sampled: the sampled lane certifies the
    # schedule-invariant PRNG chain survives recovery too
    return [Request("greedy", [1, 2, 3, 4, 5], max_new_tokens=6),
            Request("sampled", [9, 8, 7], max_new_tokens=6,
                    sampling=SamplingParams(temperature=0.8, top_k=12))]


@pytest.fixture(scope="module")
def reference_outputs(tiny_gpt):
    """The fault-free run every recovery path must reproduce exactly."""
    engine = _mk_engine(tiny_gpt)
    for r in _requests():
        engine.add_request(r)
    return engine.run()


# ---------------------------------------------------------------------------
# the FaultPlan harness itself
# ---------------------------------------------------------------------------


def test_fault_plan_is_deterministic_and_counts():
    def drive(plan):
        log = []
        for i in range(20):
            try:
                nan = plan.fire("site")
                log.append("nan" if nan else "ok")
            except TransientDispatchError:
                log.append("transient")
        return log

    specs = [FaultSpec(site="site", kind="transient", at=(2,)),
             FaultSpec(site="site", kind="transient", prob=0.3),
             FaultSpec(site="site", kind="nan", every=7, max_fires=1)]
    a, b = FaultPlan(specs, seed=11), FaultPlan(specs, seed=11)
    la, lb = drive(a), drive(b)
    assert la == lb                       # seeded => replayable
    assert la[2] == "transient"           # exact-index trigger
    assert la.count("nan") == 1           # max_fires bound
    assert drive(FaultPlan(specs, seed=12)) != la  # the seed matters
    counts = a.counts()["site"]
    assert counts["transient"] >= 1 and counts["nan"] == 1
    assert a.calls("site") == 20 and a.calls("other") == 0


def test_fault_plan_wrap_nan_corrupts_float_leaves_only():
    plan = FaultPlan([FaultSpec(site="f", kind="nan", at=(0,))])
    fn = plan.wrap("f", lambda: {"x": jnp.ones(3), "i": jnp.arange(2)})
    out = fn()
    assert np.all(np.isnan(np.asarray(out["x"])))
    np.testing.assert_array_equal(np.asarray(out["i"]), [0, 1])
    clean = fn()   # index 1: no fault
    assert not np.any(np.isnan(np.asarray(clean["x"])))


def test_fault_spec_validation():
    with pytest.raises(ValueError, match="kind"):
        FaultSpec(site="s", kind="meteor")
    with pytest.raises(ValueError, match="prob"):
        FaultSpec(site="s", kind="nan", prob=1.5)
    with pytest.raises(ValueError, match="every"):
        FaultSpec(site="s", kind="nan", every=0)
    assert nan_corrupt(jnp.int32(3)) == 3  # integers pass through


def test_engine_rejects_nan_specs_at_serving_sites(tiny_gpt):
    # serving outputs are integer tokens: a nan spec there would record
    # a fire that corrupted nothing, so construction must refuse it
    plan = FaultPlan([FaultSpec(site="decode", kind="nan", at=(0,))])
    with pytest.raises(ValueError, match="nan faults"):
        _mk_engine(tiny_gpt, faults=plan)
    # nan at the TRAIN site riding along in a shared plan is fine
    shared = FaultPlan([FaultSpec(site="train_step", kind="nan", at=(0,))])
    _mk_engine(tiny_gpt, faults=shared)


# ---------------------------------------------------------------------------
# serving: retry, quarantine, deadlines, stall guard
# ---------------------------------------------------------------------------


def test_transient_dispatch_failures_are_retried_bit_identically(
        tiny_gpt, reference_outputs):
    plan = FaultPlan([FaultSpec(site="prefill", kind="transient", at=(0,)),
                      FaultSpec(site="decode", kind="transient",
                                at=(1, 4))])
    engine = _mk_engine(tiny_gpt, faults=plan)
    for r in _requests():
        engine.add_request(r)
    out = engine.run(return_status=True)
    assert {u: r.tokens for u, r in out.items()} == reference_outputs
    assert all(r.status == "finished" for r in out.values())
    assert engine.stats()["num_dispatch_retries"] >= 3
    assert engine.stats()["num_quarantines"] == 0


def test_poisoned_prefill_is_quarantined_and_engine_survives(
        tiny_gpt, reference_outputs):
    # the FIRST request's prefill fails beyond every retry
    # (max_dispatch_retries=2 => 3 attempts); the second must sail
    # through untouched
    plan = FaultPlan([FaultSpec(site="prefill", kind="transient",
                                at=(0, 1, 2))])
    engine = _mk_engine(tiny_gpt, faults=plan)
    reqs = _requests()
    for r in reqs:
        engine.add_request(r)
    out = engine.run(return_status=True)
    assert out["greedy"].status == "failed"
    assert out["greedy"].tokens == []
    assert reqs[0].status == "failed"        # surfaced on the object too
    assert out["sampled"].status == "finished"
    assert out["sampled"].tokens == reference_outputs["sampled"]
    assert engine.stats()["num_quarantines"] == 1


def test_persistent_decode_failure_drains_lanes_without_killing_engine(
        tiny_gpt, reference_outputs):
    # two clean decode dispatches, then the site fails permanently: the
    # engine quarantines lanes youngest-first by elimination and keeps
    # running to a clean empty state instead of raising
    plan = FaultPlan([FaultSpec(site="decode", kind="transient",
                                at=tuple(range(2, 200)))])
    engine = _mk_engine(tiny_gpt, faults=plan)
    for r in _requests():
        engine.add_request(r)
    out = engine.run(return_status=True)
    assert {r.status for r in out.values()} == {"failed"}
    for uid, res in out.items():
        # tokens emitted before the failures are preserved exactly
        n = len(res.tokens)
        assert res.tokens == reference_outputs[uid][:n]
    assert engine.stats()["num_quarantines"] == 2
    assert not engine.has_work


def test_request_deadline_times_out_gracefully(tiny_gpt,
                                               reference_outputs):
    now = [0.0]
    engine = _mk_engine(tiny_gpt, clock=lambda: now[0])
    engine.add_request(Request("greedy", [1, 2, 3, 4, 5], max_new_tokens=6,
                               deadline_s=10.0))
    engine.add_request(_requests()[1])   # no deadline
    # a few ticks of progress, then the clock blows the deadline
    for _ in range(3):
        engine.step()
    now[0] = 11.0
    out = engine.run(return_status=True)
    assert out["greedy"].status == "timeout"
    n = len(out["greedy"].tokens)
    assert n < 6    # cut short...
    assert out["greedy"].tokens == reference_outputs["greedy"][:n]  # ...cleanly
    assert out["sampled"].status == "finished"
    assert out["sampled"].tokens == reference_outputs["sampled"]
    assert engine.stats()["num_timeouts"] == 1


def test_waiting_request_expires_without_ever_running(tiny_gpt):
    now = [0.0]
    engine = _mk_engine(tiny_gpt, clock=lambda: now[0])
    engine.add_request(Request("late", [1, 2, 3], max_new_tokens=4,
                               deadline_s=5.0))
    now[0] = 6.0
    out = engine.run(return_status=True)
    assert out["late"] == RequestResult(tokens=[], status="timeout")


def test_deadline_validation(tiny_gpt):
    engine = _mk_engine(tiny_gpt)
    with pytest.raises(ValueError, match="deadline_s"):
        engine.add_request(Request("bad", [1], deadline_s=0.0))


def test_midprefill_slot_expires_while_decode_in_flight(tiny_gpt):
    # an in-flight decode only covers STARTED lanes, so a mid-prefill
    # slot past its deadline must expire up front — before burning one
    # more prefill chunk — even while a dispatch is pending
    now = [0.0]
    engine = _mk_engine(tiny_gpt, clock=lambda: now[0], prefill_chunk=2)
    engine.add_request(Request("fast", [1, 2], max_new_tokens=8))
    engine.step()   # fast prefills + its decode dispatch goes in flight
    engine.add_request(Request("slowpoke", [1, 2, 3, 4, 5, 6],
                               max_new_tokens=4, deadline_s=5.0))
    engine.step()   # slowpoke admitted, chunk 1 of 3, decode in flight
    assert engine._pending is not None
    now[0] = 6.0
    chunks = engine.stats()["num_prefill_chunks"]
    engine.step()
    assert engine.statuses["slowpoke"] == "timeout"
    assert engine.stats()["num_prefill_chunks"] == chunks  # no last chunk
    out = engine.run(return_status=True)
    assert out["slowpoke"] == RequestResult(tokens=[], status="timeout")
    assert out["fast"].status == "finished"


def test_stalled_run_raises_diagnostic_not_spin(tiny_gpt):
    engine = _mk_engine(tiny_gpt)
    engine.add_request(Request("r", [1, 2, 3], max_new_tokens=2))
    engine.step = lambda: False   # a scheduler bug: work, no progress
    with pytest.raises(EngineStalledError) as ei:
        engine.run()
    assert ei.value.engine_stats["waiting"] == 1
    assert "no progress" in str(ei.value)


class _PoisonedFetch:
    """A device-array stand-in whose host fetch fails ``failures``
    times: dispatch is asynchronous, so REAL runtime errors surface at
    ``np.asarray(...)`` in the deferred drain, not at the launch the
    fault plan guards — this double injects exactly that."""

    def __init__(self, toks, failures):
        self._toks = toks
        self._failures = failures

    def __array__(self, dtype=None, copy=None):
        if self._failures:
            self._failures -= 1
            raise TransientDispatchError("injected fetch-time failure")
        return np.asarray(self._toks)


def test_fetch_time_failure_rolls_back_and_redispatches_bit_identically(
        tiny_gpt, reference_outputs):
    engine = _mk_engine(tiny_gpt)
    for r in _requests():
        engine.add_request(r)
    while engine._pending is None:
        engine.step()
    toks, active, uids = engine._pending
    engine._pending = (_PoisonedFetch(toks, 1), active, uids)
    out = engine.run(return_status=True)
    # the in-process reset requeues residents with their emitted
    # tokens and re-prefills: same tokens, nothing lost, nobody failed
    assert {u: r.tokens for u, r in out.items()} == reference_outputs
    assert {r.status for r in out.values()} == {"finished"}
    assert engine.stats()["num_dispatch_retries"] >= 1
    assert engine.stats()["num_quarantines"] == 0


def test_persistent_fetch_failure_quarantines_and_engine_survives(
        tiny_gpt, reference_outputs):
    engine = _mk_engine(tiny_gpt)
    for r in _requests():
        engine.add_request(r)
    for _ in range(3):    # let both lanes emit something first
        engine.step()
    real_decode = engine._decode

    def poisoned(*args):
        cache, toks = real_decode(*args)
        return cache, _PoisonedFetch(toks, 10 ** 9)

    engine._decode = poisoned
    out = engine.run(return_status=True)
    engine._decode = real_decode   # stats() reads the jit's cache size
    assert {r.status for r in out.values()} == {"failed"}
    for uid, res in out.items():
        n = len(res.tokens)
        assert res.tokens == reference_outputs[uid][:n]
    assert engine.stats()["num_quarantines"] == 2
    assert not engine.has_work


# ---------------------------------------------------------------------------
# serving: crash-consistent snapshot / restore
# ---------------------------------------------------------------------------


def test_chaos_certification_snapshot_restore_bit_identical(
        tiny_gpt, reference_outputs):
    """The acceptance gate: transient faults + one simulated crash;
    the engine snapshots every tick, dies, restores in a fresh engine,
    and the COMBINED outputs equal the fault-free run bit-for-bit."""
    plan = FaultPlan([FaultSpec(site="decode", kind="transient", at=(1,)),
                      FaultSpec(site="decode", kind="crash", at=(4,))])
    engine = _mk_engine(tiny_gpt, faults=plan)
    for r in _requests():
        engine.add_request(r)
    snap = None
    with pytest.raises(SimulatedCrash):
        while engine.has_work:
            engine.step()
            snap = engine.snapshot()
    assert snap is not None
    assert engine.stats()["num_dispatch_retries"] >= 1
    assert engine.stats()["num_snapshots"] >= 1
    # ... the process is gone; only `snap` survives (JSON round-trip
    # proves nothing device-resident leaked into it)
    snap = json.loads(json.dumps(snap))
    restored = _mk_engine(tiny_gpt)
    restored.restore(snap)
    assert restored.stats()["num_restores"] == 1
    out = restored.run()
    assert out == reference_outputs
    restored.check_allocator_integrity()


def test_snapshot_drains_inflight_and_carries_statuses(tiny_gpt):
    now = [0.0]
    engine = _mk_engine(tiny_gpt, clock=lambda: now[0])
    engine.add_request(Request("t", [1, 2], max_new_tokens=3,
                               deadline_s=1.0))
    engine.add_request(Request("ok", [3, 4], max_new_tokens=3))
    now[0] = 2.0
    for _ in range(3):
        engine.step()
    snap = engine.snapshot()
    assert engine._pending is None          # the drain happened
    assert snap["statuses"]["t"] == "timeout"
    assert snap["finished"]["t"] == []
    restored = _mk_engine(tiny_gpt, clock=lambda: now[0])
    restored.restore(snap)
    out = restored.run(return_status=True)
    assert out["t"].status == "timeout"
    assert out["ok"].status == "finished"


def test_restore_rejects_config_mismatch_and_used_engines(tiny_gpt):
    engine = _mk_engine(tiny_gpt)
    engine.add_request(Request("a", [1, 2, 3], max_new_tokens=2))
    engine.step()
    snap = engine.snapshot()
    other = _mk_engine(tiny_gpt, seed=8)
    with pytest.raises(ValueError, match="config mismatch"):
        other.restore(snap)
    used = _mk_engine(tiny_gpt)
    used.add_request(Request("b", [4, 5], max_new_tokens=2))
    with pytest.raises(RuntimeError, match="fresh engine"):
        used.restore(snap)
    fresh = _mk_engine(tiny_gpt)
    fresh.restore(snap)
    out = fresh.run()
    # the retry knobs are operational, not identity: restoring into an
    # engine with a bigger retry budget (the incident-recovery move the
    # snapshot exists for) must work, and outputs are unaffected
    relaxed = _mk_engine(tiny_gpt, max_dispatch_retries=7,
                         retry_backoff_s=0.25)
    relaxed.restore(snap)
    assert relaxed.run() == out


def test_allocator_prefix_index_integrity_after_restore_and_lru(tiny_gpt):
    """Refcounts and hash chains after a restore + LRU churn must be
    EXACTLY what the engine's own bookkeeping implies — and the
    restored engine must keep producing reference outputs while the
    pool evicts under pressure."""
    shared = list(range(1, 13))   # three full shared blocks
    # pool of 10: the fourth request's growth must evict LRU cached
    # chains left behind by the finished ones
    reqs = [Request(f"r{i}", shared + [50 + i], max_new_tokens=8)
            for i in range(4)]
    ref_engine = _mk_engine(tiny_gpt, num_blocks=10)
    for r in reqs:
        ref_engine.add_request(r)
    ref = ref_engine.run()

    engine = _mk_engine(tiny_gpt, num_blocks=10)
    for r in reqs[:2]:
        engine.add_request(r)
    for _ in range(4):
        engine.step()
    snap = engine.snapshot()
    # audit section is present and JSON-able
    assert set(snap["allocator"]) >= {"refcounts", "prefix_index",
                                      "evictable", "free"}
    restored = _mk_engine(tiny_gpt, num_blocks=10)
    restored.restore(json.loads(json.dumps(snap)))
    out = dict(restored.run())
    for r in reqs[2:]:            # post-restore traffic: LRU churn
        restored.add_request(r)
    out.update(restored.run())
    assert out == ref
    st = restored.stats()
    assert st["num_cache_evictions"] > 0     # LRU actually exercised
    restored.check_allocator_integrity()     # exact refcount rebuild
    # the re-prefilled prefix index recovered the shared chain: the
    # last request's prompt found cached blocks again
    assert st["prefix_hit_blocks"] > 0


def test_snapshot_counters_in_stats(tiny_gpt):
    engine = _mk_engine(tiny_gpt)
    engine.add_request(Request("a", [1, 2, 3], max_new_tokens=2))
    engine.step()
    engine.snapshot()
    st = engine.stats()
    for key in ("num_timeouts", "num_dispatch_retries", "num_quarantines",
                "num_snapshots", "num_restores"):
        assert key in st
    assert st["num_snapshots"] == 1


def test_snapshot_restores_in_fresh_process(tiny_gpt, reference_outputs,
                                            tmp_path):
    """A snapshot taken mid-stream restores in a BRAND NEW process and
    finishes bit-identically: nothing device-resident or
    interpreter-resident is load-bearing."""
    engine = _mk_engine(tiny_gpt)
    for r in _requests():
        engine.add_request(r)
    for _ in range(4):
        engine.step()
    snap = engine.snapshot()
    assert any(rec["generated"] for rec in snap["requests"]), \
        "snapshot should be mid-stream (tokens already emitted)"
    snap_file = tmp_path / "snap.json"
    snap_file.write_text(json.dumps(snap))

    script = tmp_path / "restore_and_run.py"
    script.write_text(
        "import json, sys\n"
        "import jax, jax.numpy as jnp\n"
        "from apex_tpu.models import GPTConfig, GPTLMHeadModel\n"
        "from apex_tpu.serving import EngineConfig, InferenceEngine\n"
        "cfg = GPTConfig.tiny(dropout=0.0, remat=False)\n"
        "model = GPTLMHeadModel(cfg)\n"
        "params = model.init(jax.random.PRNGKey(0),\n"
        "                    jnp.zeros((1, 8), jnp.int32))\n"
        f"engine = InferenceEngine(model, params, EngineConfig(**{ENGINE_KW!r}))\n"
        f"engine.restore(json.load(open({str(snap_file)!r})))\n"
        "out = engine.run(return_status=True)\n"
        "print(json.dumps({u: {'tokens': r.tokens, 'status': r.status}\n"
        "                  for u, r in out.items()}))\n")
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    repo = Path(__file__).resolve().parents[1]
    env["PYTHONPATH"] = str(repo)   # the script lives in tmp_path
    proc = subprocess.run([sys.executable, str(script)],
                          capture_output=True, text=True, timeout=540,
                          env=env, cwd=str(repo))
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    combined = {u: list(t) for u, t in snap["finished"].items()}
    combined.update({u: r["tokens"] for u, r in out.items()})
    assert combined == reference_outputs
    assert all(r["status"] == "finished" for r in out.values())


# ---------------------------------------------------------------------------
# serving: overload chaos (ISSUE 8 — burst + faults + deadlines)
# ---------------------------------------------------------------------------


def test_overload_burst_with_faults_never_stalls_and_bounds_queue(
        tiny_gpt):
    """A 4x burst wave through a bounded queue WITH transient faults
    and a deadline/priority mix: the engine must never stall, never let
    client adds push the queue past ``max_waiting`` (requeues of
    residents may add at most ``max_batch``), and land every accepted
    request on a terminal status."""
    plan = FaultPlan([FaultSpec(site="prefill", kind="transient", at=(1,)),
                      FaultSpec(site="decode", kind="transient",
                                at=(2, 5))])
    now = [0.0]
    engine = _mk_engine(tiny_gpt, faults=plan, clock=lambda: now[0],
                        max_waiting=4, queue_high_watermark=3,
                        degrade_patience=1)
    rng = np.random.RandomState(3)
    offered = accepted = uid = 0
    # three waves: pre / 4x burst / post
    for count in (2, 8, 2):
        for _ in range(count):
            r = Request(f"o{uid}",
                        list(rng.randint(1, 100, 3 + uid % 4)),
                        max_new_tokens=3 + uid % 3,
                        priority=uid % 2,
                        deadline_s=(1.0 if uid % 3 == 0 else None))
            offered += 1
            accepted += int(engine.try_add(r))
            uid += 1
        for _ in range(2):
            had = engine.has_work
            progressed = engine.step()
            assert progressed or not had      # the stall contract
            now[0] += 0.4
    out = engine.run(return_status=True)
    s = engine.stats()
    assert accepted < offered                 # the burst really shed
    assert s["num_rejected_queue_full"] == offered - accepted
    assert s["queue_depth_peak"] <= 4 + engine.config.max_batch
    assert len(out) == accepted               # every accepted: terminal
    assert {r.status for r in out.values()} <= {
        "finished", "timeout", "failed", "rejected"}
    assert sum(r.status == "finished" for r in out.values()) > 0
    assert s["num_dispatch_retries"] >= 1     # the faults really fired
    assert s["num_degrade_steps_down"] >= 1   # the ladder really moved
    assert not engine.has_work


def test_restore_mid_degradation_is_bit_identical(tiny_gpt):
    """Snapshot taken WHILE the degradation ladder is engaged, restored
    into a fresh engine: the ladder state rides the snapshot and the
    combined outputs equal the uninterrupted run bit-for-bit (ladder
    transitions are schedule changes; sampling is schedule-invariant,
    sampled lanes included)."""
    kw = dict(max_batch=1, queue_high_watermark=2, degrade_patience=1)

    def reqs():
        return [Request(f"r{i}", [10 + i, 20 + i, 30 + i],
                        max_new_tokens=4, priority=i % 2,
                        sampling=(SamplingParams(temperature=0.8,
                                                 top_k=12)
                                  if i == 2 else SamplingParams()))
                for i in range(4)]

    ref_engine = _mk_engine(tiny_gpt, **kw)
    for r in reqs():
        ref_engine.add_request(r)
    ref = ref_engine.run()

    engine = _mk_engine(tiny_gpt, **kw)
    for r in reqs():
        engine.add_request(r)
    while engine.stats()["degradation_level"] < 1:
        engine.step()
    snap = json.loads(json.dumps(engine.snapshot()))
    assert snap["overload"]["degradation_level"] >= 1
    restored = _mk_engine(tiny_gpt, **kw)
    restored.restore(snap)
    assert (restored.stats()["degradation_level"]
            == snap["overload"]["degradation_level"])
    combined = {u: list(t) for u, t in snap["finished"].items()}
    combined.update(restored.run())
    assert combined == ref
    restored.check_allocator_integrity()


def test_multitenant_chaos_aborts_quotas_faults_ladder(tiny_gpt):
    """The ISSUE 10 chaos gate: aborts fired mid-flight, per-tenant
    quota sheds, transient prefill/decode faults, and degradation-
    ladder steps over interleaved tenants — the engine must never
    stall, land every accepted request on a terminal status, fire
    every chaos path at least once, and leave the allocator's
    per-tenant refcount split EXACT."""
    from apex_tpu.serving import TenantQuota

    plan = FaultPlan([FaultSpec(site="prefill", kind="transient",
                                at=(1, 6)),
                      FaultSpec(site="decode", kind="transient",
                                at=(2, 7))])
    now = [0.0]
    engine = _mk_engine(
        tiny_gpt, faults=plan, clock=lambda: now[0],
        max_waiting=5, queue_high_watermark=4, degrade_patience=1,
        enable_prefix_caching=True,
        tenant_weights={"good": 3, "flood": 1},
        tenant_quotas={"flood": TenantQuota(max_waiting=2,
                                            max_resident_blocks=4)})
    rng = np.random.RandomState(17)
    uid = 0
    accepted = []
    for wave in range(6):
        for _ in range(4):
            tenant = "flood" if uid % 2 else "good"
            r = Request(f"{tenant}-{uid}",
                        list(rng.randint(1, 100, 3 + uid % 4)),
                        max_new_tokens=3 + uid % 3, tenant=tenant,
                        priority=uid % 2,
                        deadline_s=(2.0 if uid % 5 == 0 else None))
            if engine.try_add(r):
                accepted.append(r.uid)
            uid += 1
        for _ in range(2):
            had = engine.has_work
            progressed = engine.step()
            assert progressed or not had       # the stall contract
            now[0] += 0.3
        if wave % 2 and accepted:
            engine.abort(accepted[rng.randint(len(accepted))])
    out = engine.run(return_status=True)
    s = engine.stats()
    engine.check_allocator_integrity()         # the certification
    assert {r.status for r in out.values()} <= {
        "finished", "timeout", "failed", "rejected", "throttled",
        "cancelled"}
    assert s["num_cancelled"] >= 1             # aborts fired
    assert s["num_throttled"] >= 1             # quota sheds fired
    assert s["num_dispatch_retries"] >= 1      # faults fired
    assert s["num_degrade_steps_down"] >= 1    # the ladder moved
    assert sum(r.status == "finished" for r in out.values()) > 0
    # only the flood tenant was ever throttled
    throttled = {u for u, r in out.items() if r.status == "throttled"}
    assert throttled and all(u.startswith("flood") for u in throttled)
    assert not engine.has_work


# ---------------------------------------------------------------------------
# crash-safe checkpoints (ISSUE 10 satellite): a torn save is invisible
# ---------------------------------------------------------------------------


def test_torn_checkpoint_save_is_skipped_on_resume(tmp_path,
                                                   monkeypatch):
    """Kill the process between the payload write and the terminal
    marker write: ``latest_step``/``load_checkpoint`` must resume from
    the PREVIOUS complete step, never the torn one."""
    from apex_tpu.utils import checkpoint as ck

    ck.save_checkpoint(str(tmp_path), 1, params={"w": np.ones(3)})
    ck.save_checkpoint(str(tmp_path), 2, params={"w": np.full(3, 2.0)})
    assert ck.latest_step(str(tmp_path)) == 2

    def crash(*a, **k):
        raise SimulatedCrash("killed between payload and marker")

    monkeypatch.setattr(ck, "_write_marker", crash)
    with pytest.raises(SimulatedCrash):
        ck.save_checkpoint(str(tmp_path), 3,
                           params={"w": np.full(3, 3.0)})
    monkeypatch.undo()
    # the torn step-3 payload exists on disk but is invisible
    assert (tmp_path / "step_000000003").exists()
    assert ck.latest_step(str(tmp_path)) == 2
    restored = ck.load_checkpoint(str(tmp_path))
    assert restored["_step"] == 2
    np.testing.assert_array_equal(np.asarray(restored["params"]["w"]),
                                  np.full(3, 2.0))
    # explicitly naming the torn step raises rather than loading it
    with pytest.raises(FileNotFoundError, match="torn"):
        ck.load_checkpoint(str(tmp_path), step=3)
    # a clean re-save of the same step re-commits it
    ck.save_checkpoint(str(tmp_path), 3, params={"w": np.full(3, 9.0)})
    assert ck.latest_step(str(tmp_path)) == 3
    # overwrite path: the marker drops BEFORE the payload is replaced,
    # so a crash mid-overwrite reads as incomplete too
    monkeypatch.setattr(ck, "_write_marker", crash)
    with pytest.raises(SimulatedCrash):
        ck.save_checkpoint(str(tmp_path), 3,
                           params={"w": np.zeros(3)})
    monkeypatch.undo()
    assert ck.latest_step(str(tmp_path)) == 2


def test_legacy_markerless_checkpoints_stay_loadable(tmp_path):
    """A directory written entirely by the pre-marker code (no
    .complete files anywhere) keeps the old semantics: its steps are
    visible and loadable — upgrading must never orphan an existing
    run's checkpoints."""
    from apex_tpu.utils import checkpoint as ck

    ck.save_checkpoint(str(tmp_path), 4, params={"w": np.ones(2)})
    ck.save_checkpoint(str(tmp_path), 5, params={"w": np.full(2, 5.0)})
    # simulate a legacy directory by stripping the markers AND the
    # marker-era sentinel
    for f in tmp_path.glob("*.complete"):
        f.unlink()
    (tmp_path / ck._ERA_SENTINEL).unlink()
    assert ck.latest_step(str(tmp_path)) == 5
    assert ck.load_checkpoint(str(tmp_path))["_step"] == 5
    assert ck.load_checkpoint(str(tmp_path), step=4)["_step"] == 4
    # the first NEW save flips the directory to marker-governed:
    # the legacy steps (marker-less) now read as unproven
    ck.save_checkpoint(str(tmp_path), 6, params={"w": np.zeros(2)})
    assert ck.latest_step(str(tmp_path)) == 6


# ---------------------------------------------------------------------------
# training: retry, watchdog escalation, checkpoint/resume
# ---------------------------------------------------------------------------


class _Net(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.Dense(16, param_dtype=jnp.float32)(x)
        return nn.Dense(4, param_dtype=jnp.float32)(nn.relu(x))


@pytest.fixture(scope="module")
def train_setup():
    model = _Net()
    params = jax.device_get(
        model.init(jax.random.PRNGKey(0), jnp.zeros((2, 8)))["params"])

    def loss_fn(p, mb):
        x, y = mb
        logits = model.apply({"params": p}, x).astype(jnp.float32)
        onehot = jax.nn.one_hot(y, 4)
        return -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), -1))

    rng = np.random.RandomState(0)
    batches = [(jnp.asarray(rng.randn(1, 4, 8).astype("f4")),
                jnp.asarray(rng.randint(0, 4, (1, 4))))
               for _ in range(8)]
    return params, loss_fn, batches


def _fresh_loop(train_setup, amp=None, **kwargs):
    params, loss_fn, _ = train_setup
    step = build_train_step(loss_fn, FusedAdam(lr=1e-2), amp=amp,
                            accum_steps=1)
    # params are COPIED per loop: the donating step consumes its
    # state's buffers, and the module fixture must stay reusable
    return step, step.loop(step.init(jax.tree.map(jnp.asarray, params)),
                           **kwargs)


@pytest.fixture(scope="module")
def train_reference(train_setup):
    _, loop = _fresh_loop(train_setup)
    metrics = loop.run(train_setup[2])
    return jax.device_get(loop.state.params), metrics


def _assert_params_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_train_transient_retry_matches_reference(train_setup,
                                                 train_reference):
    plan = FaultPlan([FaultSpec(site="train_step", kind="transient",
                                at=(1, 5))])
    _, loop = _fresh_loop(train_setup, faults=plan)
    metrics = loop.run(train_setup[2])
    _assert_params_equal(train_reference[0],
                         jax.device_get(loop.state.params))
    assert metrics == train_reference[1]
    assert loop.stats()["dispatch_retries"] == 2


def test_train_retry_exhaustion_raises_and_finally_drains(train_setup):
    plan = FaultPlan([FaultSpec(site="train_step", kind="transient",
                                at=tuple(range(2, 40)))])
    _, loop = _fresh_loop(train_setup, faults=plan, max_retries=1)
    with pytest.raises(DispatchFailedError, match="train_step"):
        loop.run(train_setup[2])
    # steps 0 and 1 completed; the finally-drain preserved BOTH their
    # metrics even though run() unwound mid-iteration
    assert len(loop.last_run_metrics) == 2
    assert [m["step"] for m in loop.last_run_metrics] == [1, 2]
    assert loop.stats()["dispatch_retries"] == 1


def test_watchdog_ladder_skip_rescale_halt(train_setup):
    from apex_tpu.amp.scaler import LossScaler

    plan = FaultPlan([FaultSpec(site="train_step", kind="nan", every=1)])
    # a dynamic scaler (init 2**16) so the rescale rung's halving is
    # observable — with amp=None the static unity scale is already at
    # the floor
    _, loop = _fresh_loop(
        train_setup, amp=LossScaler(), faults=plan,
        watchdog=WatchdogConfig(skip_steps=1, rescale_steps=2,
                                min_scale=1.0))
    scale0 = float(jax.device_get(loop.state.scaler_state.loss_scale))
    with pytest.raises(NonFiniteLossError) as ei:
        loop.run(train_setup[2])
    s = loop.stats()
    assert (s["watchdog_skips"], s["watchdog_rescales"],
            s["watchdog_halts"]) == (1, 2, 1)
    assert s["watchdog_nonfinite"] >= 4
    # the rescale rung really halved the scale, twice
    scale1 = float(jax.device_get(loop.state.scaler_state.loss_scale))
    assert scale1 == scale0 / 4
    assert math.isnan(float(ei.value.metrics["loss"]))
    assert ei.value.loop_stats["watchdog_rescales"] == 2
    # the halting run still surfaced every fetched step's metrics
    assert loop.last_run_metrics


def test_watchdog_halts_when_threshold_crossed_on_final_step(train_setup):
    # the halt rung first crossed by the LAST step's metrics is seen by
    # the completed-run drain, which must still raise — a wedged run
    # must never return as success just because it ran out of batches
    plan = FaultPlan([FaultSpec(site="train_step", kind="nan", every=1)])
    _, loop = _fresh_loop(
        train_setup, faults=plan,
        watchdog=WatchdogConfig(skip_steps=3, rescale_steps=3))
    with pytest.raises(NonFiniteLossError):
        loop.run(train_setup[2][:7])
    s = loop.stats()
    assert s["watchdog_halts"] == 1
    assert len(loop.last_run_metrics) == 6   # m1..m6; m7 is the halt


def test_watchdog_recovers_when_loss_turns_finite(train_setup):
    # non-finite for 2 steps, then clean: the ladder resets instead of
    # climbing to a halt
    plan = FaultPlan([FaultSpec(site="train_step", kind="nan", at=(1, 2))])
    _, loop = _fresh_loop(
        train_setup, faults=plan,
        watchdog=WatchdogConfig(skip_steps=2, rescale_steps=1))
    loop.run(train_setup[2])
    s = loop.stats()
    assert s["watchdog_skips"] == 2
    assert s["watchdog_rescales"] == 0 and s["watchdog_halts"] == 0


def test_chaos_certification_checkpoint_resume_bit_identical(
        train_setup, train_reference, tmp_path):
    """The training acceptance gate: transient faults + a crash; resume
    from the periodic checkpoint reproduces the uninterrupted final
    params bit-for-bit."""
    plan = FaultPlan([FaultSpec(site="train_step", kind="transient",
                                at=(2,)),
                      FaultSpec(site="train_step", kind="crash", at=(7,))])
    step, loop = _fresh_loop(train_setup, faults=plan,
                             checkpoint_dir=str(tmp_path),
                             checkpoint_every=2)
    with pytest.raises(SimulatedCrash):
        loop.run(train_setup[2])
    s = loop.stats()
    assert s["dispatch_retries"] >= 1
    assert s["checkpoints_saved"] >= 1
    assert s["last_checkpoint_step"] is not None

    step2, loop2 = _fresh_loop(train_setup)
    state, k = load_train_state(str(tmp_path), loop2.state)
    assert k == s["last_checkpoint_step"]
    resumed = TrainLoop(step2, state)
    resumed.run(train_setup[2][k:])
    _assert_params_equal(train_reference[0],
                         jax.device_get(resumed.state.params))
