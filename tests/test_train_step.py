"""apex_tpu.train: the fused single-dispatch train step.

The certification contract (ISSUE 5, the greedy analog of the serving
cross-K certification): the fused scanned-accumulation step must be
BIT-IDENTICAL to the hand-wired per-microbatch dispatch loop it
replaces — across amp opt levels, DDP flat-buffer modes, optimizers,
and through an overflow-skip step mid-run — and the compiled program
must POSITIVELY show donated buffers aliasing (XLA drops donation with
only a warning, so absence-of-error proves nothing).
"""

import json
import math
import os
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import flax.linen as nn

import apex_tpu.amp as amp
from apex_tpu.optimizers import FusedAdam, FusedLAMB
from apex_tpu.optimizers._base import FusedOptimizer
from apex_tpu.parallel import DistributedDataParallel
from apex_tpu.train import (
    TrainLoop,
    build_reference_loop,
    build_train_step,
)
from apex_tpu.utils.hlo_audit import input_output_alias_stats


class Net(nn.Module):
    """Small net WITH a norm-named layer so O2's keep_batchnorm_fp32
    path exercises a mixed fp32/bf16 param tree."""

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(32, param_dtype=jnp.float32)(x)
        x = nn.LayerNorm(param_dtype=jnp.float32)(x)
        x = nn.relu(x)
        return nn.Dense(4, param_dtype=jnp.float32)(x)


def _data(accum, batch, feat=16, seed=0):
    rng = np.random.RandomState(seed)
    xs = jnp.asarray(rng.randn(accum, batch, feat).astype("f4"))
    ys = jnp.asarray(rng.randint(0, 4, (accum, batch)))
    return xs, ys


def _loss_fn(model):
    def loss_fn(p, mb):
        x, y = mb
        logits = model.apply({"params": p}, x).astype(jnp.float32)
        lp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(lp, y[:, None], 1))

    return loss_fn


def _setup(opt_level, optimizer, seed=0):
    model = Net()
    xs, ys = _data(4, 8, seed=seed)
    params = model.init(jax.random.PRNGKey(1), xs[0])["params"]
    params, opt, handle = amp.initialize(
        params, optimizer, opt_level=opt_level, verbosity=0)
    return model, params, opt, handle, (xs, ys)


def _trees_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb))


def _copy(tree):
    return jax.tree.map(jnp.copy, tree)


# ---------------------------------------------------------------------------
# fused vs hand-wired reference: single device
# ---------------------------------------------------------------------------


def test_fused_matches_reference_single_device():
    model, p0, opt, handle, batch = _setup("O1", FusedAdam(lr=1e-2))
    loss_fn = _loss_fn(model)
    ts = build_train_step(loss_fn, opt, amp=handle, accum_steps=4)
    ref = build_reference_loop(loss_fn, opt, amp=handle, accum_steps=4)
    sA, sB = ts.init(_copy(p0)), ref.init(_copy(p0))
    for _ in range(6):
        sA, mA = ts.step(sA, batch)
        sB, mB = ref.step(sB, batch)
    assert _trees_equal(sA.params, sB.params)
    assert _trees_equal(sA.opt_state, sB.opt_state)
    assert _trees_equal(sA.scaler_state, sB.scaler_state)
    # metrics contract: device scalars with the documented keys
    for key in ("loss", "loss_scale", "skipped", "steps_skipped", "step"):
        assert key in mA, key
        assert np.asarray(mA[key]).ndim == 0
    assert int(np.asarray(mA["step"])) == 6
    assert float(np.asarray(mA["loss"])) == pytest.approx(
        float(np.asarray(mB["loss"])))


def test_accum_steps_one_matches_reference():
    model, p0, opt, handle, (xs, ys) = _setup("O1", FusedAdam(lr=1e-2))
    loss_fn = _loss_fn(model)
    batch = (xs[:1], ys[:1])
    ts = build_train_step(loss_fn, opt, amp=handle, accum_steps=1)
    ref = build_reference_loop(loss_fn, opt, amp=handle, accum_steps=1)
    sA, sB = ts.init(_copy(p0)), ref.init(_copy(p0))
    for _ in range(4):
        sA, _ = ts.step(sA, batch)
        sB, _ = ref.step(sB, batch)
    assert _trees_equal(sA.params, sB.params)


# ---------------------------------------------------------------------------
# cross-composition: amp {O1,O2} x DDP delay_allreduce x {Adam, LAMB}
# with an overflow-skip step mid-run (the L1 cross-product, composed
# through the builder and bit-compared against the hand-wired loop)
# ---------------------------------------------------------------------------


def _assert_certified_equal(treeA, treeB, opt_level):
    """The certification tier each composition can honestly hold.

    O1 trees (uniform f32 graph) and every bf16 leaf: BIT identity.
    The fp32 values of an O2 (mixed-precision) composition under
    shard_map — kept-fp32 norm leaves, fp32 moments, fp32 masters:
    drift-bounded agreement only. Bisected root cause: XLA:CPU's
    fusion/FMA contraction compiles fp32 arithmetic of a MIXED-
    precision SPMD graph with different last-bit rounding in a scan
    body than in a standalone program (the divergence appears in the
    per-microbatch gradient itself, pre-reduction; no barrier/unroll
    placement removes it, while two standalone programs agree). The
    same compositions are fully bit-identical single-device (test
    below), so the concession is an SPMD-compilation artifact, not an
    accumulation-semantics one. The tolerance is ulp-drift-scale: a
    real composition bug (wrong averaging, doubled allreduce, missed
    unscale) is off by 1e-1 .. 65536x, not 1e-3."""
    for a, b in zip(jax.tree.leaves(treeA), jax.tree.leaves(treeB)):
        a, b = np.asarray(a), np.asarray(b)
        if opt_level == "O1" or a.dtype != np.float32:
            assert np.array_equal(a, b)
        else:
            np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-6)


@pytest.mark.parametrize("opt_level", ["O1", "O2"])
@pytest.mark.parametrize("delay", [False, True])
@pytest.mark.parametrize("opt_cls", [FusedAdam, FusedLAMB])
def test_cross_composition_ddp(opt_level, delay, opt_cls):
    model, p0, opt, handle, (xs, ys) = _setup(
        opt_level, opt_cls(lr=1e-2), seed=3)
    loss_fn = _loss_fn(model)
    mesh = jax.make_mesh((8,), ("data",))
    ddp = DistributedDataParallel(axis_name="data",
                                  delay_allreduce=delay,
                                  message_size=64)
    kw = dict(amp=handle, ddp=ddp, accum_steps=4, mesh=mesh)
    ts = build_train_step(loss_fn, opt, **kw)
    ref = build_reference_loop(loss_fn, opt, **kw)
    sA, sB = ts.init(_copy(p0)), ref.init(_copy(p0))
    # poison ONE microbatch's input (one device's shard) at step 2: the
    # overflow must skip the whole global step on EVERY device, back
    # the scale off once, and leave params/moments untouched — in both
    # programs
    xs_bad = xs.at[2, 5, :].set(jnp.inf)
    for t in range(5):
        batch = (xs_bad if t == 2 else xs, ys)
        sA, mA = ts.step(sA, batch)
        sB, mB = ref.step(sB, batch)
    _assert_certified_equal(sA.params, sB.params, opt_level)
    _assert_certified_equal(sA.opt_state, sB.opt_state, opt_level)
    assert _trees_equal(sA.scaler_state, sB.scaler_state)
    assert int(np.asarray(sA.scaler_state.steps_skipped)) == 1
    assert float(np.asarray(sA.scaler_state.loss_scale)) == 2.0 ** 15
    assert int(np.asarray(mA["step"])) == 5


@pytest.mark.parametrize("opt_cls", [FusedAdam, FusedLAMB])
def test_o2_ddp_bit_identity_uniform_cast_net(opt_cls):
    """O2 + DDP, norm-free net: every PARAM leaf casts to bf16 and the
    fused-vs-hand-wired params stay BIT-identical through master
    weights + the overflow skip; the fp32 optimizer state rides the
    drift-bounded tier (see _assert_certified_equal)."""

    class DenseNet(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(32, param_dtype=jnp.float32)(x)
            x = nn.relu(x)
            return nn.Dense(4, param_dtype=jnp.float32)(x)

    model = DenseNet()
    xs, ys = _data(4, 8, seed=5)
    p0 = model.init(jax.random.PRNGKey(1), xs[0])["params"]
    p0, opt, handle = amp.initialize(
        p0, opt_cls(lr=1e-2), opt_level="O2", verbosity=0)
    loss_fn = _loss_fn(model)
    mesh = jax.make_mesh((8,), ("data",))
    ddp = DistributedDataParallel(axis_name="data", delay_allreduce=True)
    kw = dict(amp=handle, ddp=ddp, accum_steps=4, mesh=mesh)
    ts = build_train_step(loss_fn, opt, **kw)
    ref = build_reference_loop(loss_fn, opt, **kw)
    sA, sB = ts.init(_copy(p0)), ref.init(_copy(p0))
    xs_bad = xs.at[1, 3, :].set(jnp.nan)
    for t in range(5):
        batch = (xs_bad if t == 2 else xs, ys)
        sA, _ = ts.step(sA, batch)
        sB, _ = ref.step(sB, batch)
    assert _trees_equal(sA.params, sB.params)       # bf16: bitwise
    _assert_certified_equal(sA.opt_state, sB.opt_state, "O2")
    assert _trees_equal(sA.scaler_state, sB.scaler_state)
    assert int(np.asarray(sA.scaler_state.steps_skipped)) == 1


def test_o2_single_device_keep_norm_fp32_bit_identity():
    """O2 with the fp32-kept norm leaves IS bit-identical single-device
    (the ulp concession in _assert_certified_equal is strictly an
    SPMD-compilation artifact, not an accumulation-semantics one)."""
    model, p0, opt, handle, batch = _setup("O2", FusedAdam(lr=1e-2))
    loss_fn = _loss_fn(model)
    ts = build_train_step(loss_fn, opt, amp=handle, accum_steps=4)
    ref = build_reference_loop(loss_fn, opt, amp=handle, accum_steps=4)
    sA, sB = ts.init(_copy(p0)), ref.init(_copy(p0))
    for _ in range(5):
        sA, _ = ts.step(sA, batch)
        sB, _ = ref.step(sB, batch)
    assert _trees_equal(sA.params, sB.params)
    assert _trees_equal(sA.opt_state, sB.opt_state)


def test_overflow_step_leaves_state_untouched():
    model, p0, opt, handle, (xs, ys) = _setup("O1", FusedAdam(lr=1e-2))
    loss_fn = _loss_fn(model)
    ts = build_train_step(loss_fn, opt, amp=handle, accum_steps=4)
    state = ts.init(_copy(p0))
    state, _ = ts.step(state, (xs, ys))
    params_before = _copy(state.params)
    moments_before = _copy(state.opt_state.exp_avg)
    state, m = ts.step(state, (xs.at[0, 0, 0].set(jnp.nan), ys))
    assert bool(np.asarray(m["skipped"]))
    assert _trees_equal(state.params, params_before)
    assert _trees_equal(state.opt_state.exp_avg, moments_before)
    assert int(np.asarray(m["steps_skipped"])) == 1
    # but the step counter in metrics still advanced (a skipped step is
    # a consumed batch, matching the reference's epoch accounting)
    assert int(np.asarray(m["step"])) == 2


# ---------------------------------------------------------------------------
# donation: the compiled program must SHOW the aliasing
# ---------------------------------------------------------------------------


def test_donation_aliases_params_and_moments():
    model, p0, opt, handle, batch = _setup("O2", FusedAdam(lr=1e-2))
    ts = build_train_step(_loss_fn(model), opt, amp=handle,
                          accum_steps=4)
    state = ts.init(_copy(p0))
    stats = ts.alias_stats(state, batch)
    n_params = len(jax.tree.leaves(state.params))
    n_state = len(jax.tree.leaves(state))
    # every param leaf AND at least the moment/master/scaler buffers
    # must alias; a dropped donation (layout mismatch) shows up here as
    # a hard count, not an XLA warning
    assert stats["pairs"] >= n_params + 1
    assert stats["pairs"] <= n_state
    assert set(stats["kinds"]) <= {"may-alias", "must-alias"}
    # and the audit is a positive signal: the undonated build aliases 0
    ts_nodonate = build_train_step(_loss_fn(model), opt, amp=handle,
                                   accum_steps=4, donate=False)
    assert ts_nodonate.alias_stats(ts_nodonate.init(_copy(p0)),
                                   batch)["pairs"] == 0


def test_donated_state_is_consumed():
    model, p0, opt, handle, batch = _setup("O1", FusedAdam(lr=1e-2))
    ts = build_train_step(_loss_fn(model), opt, amp=handle,
                          accum_steps=4)
    state = ts.init(_copy(p0))
    old_leaf = jax.tree.leaves(state.params)[0]
    new_state, _ = ts.step(state, batch)
    with pytest.raises(RuntimeError):
        np.asarray(old_leaf)  # buffer was donated into new_state
    assert np.all(np.isfinite(np.asarray(jax.tree.leaves(
        new_state.params)[0])))


def test_input_output_alias_stats_parses_header():
    text = ("HloModule jit_step, is_scheduled=true, input_output_alias="
            "{ {0}: (0, {}, may-alias), {1}: (2, {}, must-alias) }, "
            "entry_computation_layout={(f32[4]{0})->(f32[4]{0})}")
    stats = input_output_alias_stats(text)
    assert stats["pairs"] == 2
    assert stats["params"] == [0, 2]
    assert stats["kinds"] == {"may-alias": 1, "must-alias": 1}
    assert input_output_alias_stats("HloModule bare")["pairs"] == 0


# ---------------------------------------------------------------------------
# deferred metrics loop
# ---------------------------------------------------------------------------


def test_train_loop_defers_metrics_by_one_step():
    model, p0, opt, handle, batch = _setup("O1", FusedAdam(lr=1e-2))
    ts = build_train_step(_loss_fn(model), opt, amp=handle,
                          accum_steps=4)
    # ground truth: the same stream, fetched eagerly
    eager_losses = []
    s = ts.init(_copy(p0))
    for _ in range(5):
        s, m = ts.step(s, batch)
        eager_losses.append(float(np.asarray(m["loss"])))

    loop = TrainLoop(ts, ts.init(_copy(p0)))
    got = []
    assert loop.step(batch) is None       # nothing pending on call 1
    for _ in range(4):
        m = loop.step(batch)
        assert isinstance(m["loss"], float)   # host scalars, not arrays
        assert isinstance(m["step"], int)
        got.append(m["loss"])
    final = loop.drain()
    got.append(final["loss"])
    assert loop.drain() is None
    assert got == eager_losses
    assert final["step"] == 5
    assert int(np.asarray(loop.state.step)) == 5


def test_train_loop_run_collects_all_metrics():
    model, p0, opt, handle, batch = _setup("O1", FusedAdam(lr=1e-2))
    ts = build_train_step(_loss_fn(model), opt, amp=handle,
                          accum_steps=4)
    loop = ts.loop(ts.init(_copy(p0)))
    out = loop.run([batch] * 4)
    assert [m["step"] for m in out] == [1, 2, 3, 4]


# ---------------------------------------------------------------------------
# builder knobs
# ---------------------------------------------------------------------------


def test_lr_schedule_and_grad_norm():
    model, p0, opt, handle, batch = _setup("O1", FusedAdam(lr=1e-2))
    loss_fn = _loss_fn(model)
    # lr schedule pinned to 0: params must not move, but moments do
    ts = build_train_step(loss_fn, opt, amp=handle, accum_steps=4,
                          lr_schedule=lambda step: 0.0,
                          with_grad_norm=True)
    state = ts.init(_copy(p0))
    new_state, m = ts.step(state, batch)
    assert _trees_equal(new_state.params, p0)
    # ...but the step still ran: moments moved off zero
    assert not _trees_equal(
        new_state.opt_state.exp_avg,
        jax.tree.map(jnp.zeros_like, new_state.opt_state.exp_avg))
    assert float(np.asarray(m["grad_norm"])) > 0


def test_batch_shape_validation():
    model, p0, opt, handle, (xs, ys) = _setup("O1", FusedAdam(lr=1e-2))
    ts = build_train_step(_loss_fn(model), opt, amp=handle,
                          accum_steps=8)
    state = ts.init(_copy(p0))
    with pytest.raises(ValueError, match="accum_steps=8"):
        ts.step(state, (xs, ys))  # xs has leading dim 4, not 8


def test_has_aux_surfaces_in_metrics():
    model, p0, opt, handle, batch = _setup("O1", FusedAdam(lr=1e-2))

    def loss_fn(p, mb):
        x, y = mb
        logits = model.apply({"params": p}, x).astype(jnp.float32)
        lp = jax.nn.log_softmax(logits)
        loss = -jnp.mean(jnp.take_along_axis(lp, y[:, None], 1))
        return loss, jnp.argmax(logits, -1)

    ts = build_train_step(loss_fn, opt, amp=handle, accum_steps=4,
                          has_aux=True)
    _, m = ts.step(ts.init(_copy(p0)), batch)
    assert np.asarray(m["aux"]).shape == (4, 8)  # stacked per microbatch


def test_has_aux_gathers_all_devices_under_ddp():
    """aux is device-varying; under DDP the builder must all_gather it
    to an explicit leading device axis, not let an undefined single
    shard survive the replicated out_spec."""
    model, p0, opt, handle, (xs, ys) = _setup("O1", FusedAdam(lr=1e-2))

    def loss_fn(p, mb):
        x, y = mb
        logits = model.apply({"params": p}, x).astype(jnp.float32)
        lp = jax.nn.log_softmax(logits)
        loss = -jnp.mean(jnp.take_along_axis(lp, y[:, None], 1))
        return loss, jnp.argmax(logits, -1)

    mesh = jax.make_mesh((8,), ("data",))
    ddp = DistributedDataParallel(axis_name="data")
    ts = build_train_step(loss_fn, opt, amp=handle, ddp=ddp,
                          accum_steps=4, mesh=mesh, has_aux=True)
    _, m = ts.step(ts.init(_copy(p0)), (xs, ys))
    aux = np.asarray(m["aux"])
    assert aux.shape == (8, 4, 1)  # [world, accum, local batch]
    # every device's shard present: the 8 local predictions reassemble
    # the global batch of 8
    assert sorted(aux.reshape(8, 4)[:, 0].tolist()) == sorted(
        np.asarray(jnp.argmax(
            model.apply({"params": p0}, xs[0]).astype(jnp.float32),
            -1)).tolist())


def test_scaler_none_is_unity_static():
    model, p0, opt, handle, batch = _setup("O0", FusedAdam(lr=1e-2))
    ts = build_train_step(_loss_fn(model), opt, amp=None, accum_steps=4)
    state, m = ts.step(ts.init(_copy(p0)), batch)
    assert float(np.asarray(m["loss_scale"])) == 1.0
    assert not bool(np.asarray(m["skipped"]))


# ---------------------------------------------------------------------------
# donation-friendly optimizer apply surface
# ---------------------------------------------------------------------------


def test_apply_gradients_uniform_across_optimizers():
    p = {"w": jnp.ones((4,), jnp.float32)}
    g = {"w": jnp.full((4,), 0.1, jnp.float32)}
    for opt in (FusedAdam(lr=1e-2), FusedLAMB(lr=1e-2)):
        st = opt.init(p)
        out = opt.apply_gradients(g, st, p)
        assert len(out) == 2  # always (params, state), never a 3-tuple
        # grad_scale folds in natively (LAMB) or via pre-unscale (Adam)
        out2 = opt.apply_gradients(
            jax.tree.map(lambda x: x * 8.0, g), opt.init(p), p,
            grad_scale=8.0)
        assert len(out2) == 2
        np.testing.assert_allclose(np.asarray(out[0]["w"]),
                                   np.asarray(out2[0]["w"]), rtol=1e-6)


def test_apply_gradients_rejects_alias_breaking_update():
    class BadOpt(FusedOptimizer):
        def init(self, params):
            return {}

        def step(self, grads, state, params, skip_if=None, lr=None):
            # dtype drift: a donated f32 buffer can't alias f16 output
            return jax.tree.map(lambda p: p.astype(jnp.float16), params), {}

    p = {"w": jnp.ones((4,), jnp.float32)}
    with pytest.raises(ValueError, match="donated buffer"):
        BadOpt().apply_gradients(p, {}, p)


def test_allreduce_accumulated_divides_then_syncs_once():
    from apex_tpu.utils.collectives import compat_shard_map

    mesh = jax.make_mesh((8,), ("data",))
    ddp = DistributedDataParallel(axis_name="data")
    stacked = jnp.stack([jnp.full((4,), float(i + 1)) for i in range(8)])

    def f(acc):
        return ddp.allreduce_accumulated(
            jax.tree.map(lambda x: x[0], acc), 2)

    out = jax.jit(compat_shard_map(
        f, mesh, in_specs=P("data"), out_specs=P()))(stacked)
    # mean over devices of (per-device sum / accum=2): mean(1..8)/2
    np.testing.assert_allclose(np.asarray(out),
                               np.full((4,), 4.5 / 2.0), rtol=1e-6)


# ---------------------------------------------------------------------------
# bench section smoke (CI satellite: no more blank bench rounds)
# ---------------------------------------------------------------------------


def _load_bench():
    import importlib.util

    path = Path(__file__).resolve().parents[1] / "bench.py"
    spec = importlib.util.spec_from_file_location("_bench_train_smoke",
                                                 path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_train_step_section_smoke():
    """The bench train-step sweep (fast shape) must run end-to-end,
    certify fused-vs-loop bit identity, and report a positive donation
    audit."""
    rec = _load_bench().bench_train_step(fast=True)
    assert rec["unit"] == "steps/sec"
    assert rec["final_params_bit_identical"] is True
    assert rec["donated_alias_pairs"] >= 1
    assert rec["accum_steps_swept"] == [1, 4]
    for arm in rec["sweep"].values():
        assert arm["bit_identical"] is True
        assert arm["fused_steps_per_sec"] > 0
        assert arm["loop_steps_per_sec"] > 0
    assert rec["value"] > 0 and rec["vs_baseline"] > 0


def test_bench_smoke_mode_every_section_rc0():
    """``bench.py --smoke`` (the tier-1 guard against BENCH_r01/r05-
    style blank rounds: rc=1, parsed: null) must exit 0 with one valid
    JSON record per section."""
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    repo = Path(__file__).resolve().parents[1]
    out = subprocess.run(
        [sys.executable, str(repo / "bench.py"), "--smoke"],
        capture_output=True, text=True, timeout=900, env=env,
        cwd=str(repo))
    assert out.returncode == 0, out.stderr[-2000:]
    records = [json.loads(line) for line in
               out.stdout.strip().splitlines()]
    metrics = {r["metric"] for r in records if "metric" in r}
    assert metrics == {
        "fused_layer_norm_fwdbwd_speedup_vs_xla",
        "fused_lamb_step_speedup_vs_per_leaf_eager",
        "ddp_syncbn_allreduce_bytes_over_grad_bytes_8dev",
        "serving_tiny_smoke_decode_steps_per_sec",
        "serving_tiny_smoke_multistep_decode_tokens_per_sec",
        "serving_tiny_speculative_decode_tokens_per_sec",
        "serving_tiny_overload_goodput_tokens_per_sec",
        "serving_tiny_multitenant_victim_goodput_tok_per_sec",
        "serving_tiny_kv_memory_int8_decode_tokens_per_sec",
        "serving_tiny_weight_quant_int8_decode_tokens_per_sec",
        "serving_tiny_fleet_kill_goodput_tok_per_sec",
        "serving_tiny_integrity_sdc_detection_latency_ticks",
        "serving_tiny_mesh_decode_tokens_per_sec",
        "serving_tiny_process_kill_goodput_tok_per_sec",
        "serving_tiny_disagg_ttft_p99_ticks",
        "serving_tiny_shared_prefix_fleet_hit_rate",
        "train_step_tiny_smoke_fused_steps_per_sec",
        "train_tiny_sharded_steps_per_sec",
        "obs_pipeline_smoke_requests_summarized",
    }
    for r in records:
        if "metric" in r:
            assert "value" in r and "vs_baseline" in r, r["metric"]
    # the speculative arm must actually speculate in smoke shape: a
    # zero acceptance count would mean the drafter is silently off and
    # the record a quiet perf lie
    spec = [r for r in records
            if r.get("metric") == "serving_tiny_speculative_decode_tokens_per_sec"][0]
    assert spec["acceptance_rate"] > 0, spec
    assert spec["arms"]["speculative"]["num_accepted_tokens"] > 0, spec
    assert spec["outputs_bit_identical"] is True, spec
    # the overload arm's latency percentiles and goodput must be
    # present and FINITE (the r01/r05 dead-section lesson extended to
    # the tail-latency arm: a NaN percentile is a quiet perf lie), with
    # zero engine stalls and the queue bound respected
    ov = [r for r in records
          if r.get("metric") == "serving_tiny_overload_goodput_tokens_per_sec"][0]
    for key in ("p50_ttft_s", "p99_ttft_s", "p50_itl_s", "p99_itl_s",
                "goodput_tokens_per_sec", "decode_tokens_per_sec",
                "slo_attainment"):
        assert key in ov and math.isfinite(ov[key]), (key, ov)
    assert ov["num_stalls"] == 0, ov
    assert ov["queue_depth_peak"] <= ov["max_waiting"] + ov["max_batch"]
    assert ov["status_counts"].get("finished", 0) > 0, ov
    # the multitenant arm must have actually confined the flood (the
    # in-section asserts do the heavy lifting; here we pin the record
    # shape so a silently-skipped phase cannot pass)
    mt = [r for r in records
          if r.get("metric")
          == "serving_tiny_multitenant_victim_goodput_tok_per_sec"][0]
    assert mt["flood_only_shed"] is True, mt
    assert mt["allocator_integrity_ok"] is True, mt
    assert mt["chaos_aborts"] > 0 and mt["chaos_retries"] > 0, mt
    for t in ("acme", "bolt"):
        assert mt["per_tenant"][t]["door_sheds"] == 0, mt
        assert mt["per_tenant"][t]["throttled"] == 0, mt
        assert mt["per_tenant"][t]["goodput_tokens"] > 0, mt
    assert math.isfinite(mt["vs_baseline"]), mt
    # the kv-memory arm (docs/serving.md memory tiers) must show
    # quantization buying REAL concurrency under an equal byte budget
    # and the spill tier actually re-admitting on the re-serve pass —
    # a silently-skipped phase or a zero hit rate is a quiet capacity
    # lie
    km = [r for r in records
          if r.get("metric")
          == "serving_tiny_kv_memory_int8_decode_tokens_per_sec"][0]
    assert km["residents_ratio"] >= 1.5, km
    assert km["int8"]["peak_residents"] > km["fp"]["peak_residents"], km
    assert km["int8"]["num_blocks"] > km["fp"]["num_blocks"], km
    assert km["spill"]["hit_rate"] > 0, km
    assert km["spill"]["blocks_spilled"] > 0, km
    assert km["spill"]["reserve_token_identical"] is True, km
    assert math.isfinite(km["value"]) and km["value"] > 0, km
    # the weight-quant arm (docs/serving.md "Quantized weight
    # storage") must prove the capacity headline (>= 1.8x model bytes
    # per chip at an equal HBM budget) AND the greedy token-identity
    # cert — a non-asserting arm would be a quiet numerics lie
    wq = [r for r in records
          if r.get("metric")
          == "serving_tiny_weight_quant_int8_decode_tokens_per_sec"][0]
    assert wq["bytes_ratio"] >= 1.8, wq
    assert wq["vs_baseline"] == wq["bytes_ratio"], wq
    assert wq["int8_residents"] > wq["fp_residents"], wq
    assert wq["int8_param_bytes"] < wq["fp_param_bytes"], wq
    assert wq["greedy_token_identical"] is True, wq
    assert wq["int8"]["decode_tokens"] > 0, wq
    assert math.isfinite(wq["value"]) and wq["value"] > 0, wq
    # the fleet arm (docs/fleet.md) must prove the crash-tolerance
    # headline: a 1-replica fleet bit-identical to the bare engine, a
    # replica killed mid-burst with ZERO lost accepted requests,
    # failover + drain-and-migrate both actually fired, and the
    # victims' p99 TTFT inside its bound vs the no-kill baseline — a
    # silently-skipped kill would be a quiet robustness lie
    flr = [r for r in records
           if r.get("metric")
           == "serving_tiny_fleet_kill_goodput_tok_per_sec"][0]
    assert flr["identity_ok"] is True, flr
    assert flr["zero_lost"] is True, flr
    assert flr["num_lost_requests"] == 0, flr
    assert flr["num_failovers"] >= 1, flr
    assert flr["num_migrations"] >= 1, flr
    assert flr["num_accepted"] > 0, flr
    assert (flr["victim_p99_ttft_ticks"]
            <= flr["victim_p99_bound_ticks"]), flr
    assert flr["status_counts"].get("finished", 0) > 0, flr
    assert flr["allocator_integrity_ok"] is True, flr
    assert math.isfinite(flr["vs_baseline"]) and flr["value"] > 0, flr
    # the data-integrity arm (docs/robustness.md "Data integrity")
    # must prove the whole detection story: integrity-off bit-identity
    # held, spill rot was detected AND served token-identically by
    # recompute, the fleet-wide artifact chaos lost nothing while
    # catching every fired corruption, and the SDC-faulted replica was
    # caught by the cross-check with a real (finite, nonnegative)
    # detection latency — a silently-skipped phase would be a quiet
    # integrity lie
    it = [r for r in records
          if r.get("metric")
          == "serving_tiny_integrity_sdc_detection_latency_ticks"][0]
    assert it["identity_ok"] is True, it
    assert it["spill_corrupt_discards"] > 0, it
    assert it["spill_served_token_identical"] is True, it
    assert it["chaos_detections"] > 0, it
    assert it["chaos_zero_lost"] is True, it
    assert it["sdc_suspects"] >= 1, it
    assert it["sdc_checks"] >= 1, it
    assert it["sdc_zero_lost"] is True and it["sdc_exactly_once"] is True
    assert math.isfinite(it["value"]) and it["value"] >= 0, it
    assert it["sdc_suspect_tick"] >= it["sdc_first_corrupt_tick"], it
    assert math.isfinite(it["vs_baseline"]) and it["vs_baseline"] > 0
    # the mesh arm (docs/serving.md "Mesh sharding") must prove the
    # pod-scale promotion story: (1,1) bit-identical to the pre-mesh
    # engine, greedy outputs token-identical across mesh shapes,
    # compile counts pinned at one per program under BOTH meshes, and
    # the collective contract (zero at (1,1), all-reduce traffic in
    # every program at (1,2)) — a silently-single-device arm would be
    # a quiet scale-up lie
    ms = [r for r in records
          if r.get("metric") == "serving_tiny_mesh_decode_tokens_per_sec"][0]
    assert ms["mesh11_bit_identical"] is True, ms
    assert ms["cross_mesh_token_identical"] is True, ms
    for arm_name in ("mesh_1x1", "mesh_1x2"):
        arm = ms["arms"][arm_name]
        assert arm["prefill_compilations"] == 1, ms
        assert arm["decode_compilations"] == 1, ms
    assert all(v == 0 for v in
               ms["arms"]["mesh_1x1"]["collective_ops"].values()), ms
    # reduction_ops, not the raw all-reduce count: XLA may spell one
    # all-reduce as a reduce-scatter + all-gather pair (the hlo_audit
    # round-5 lesson) and both spellings satisfy the contract
    assert all(v >= 1 for v in
               ms["arms"]["mesh_1x2"]["reduction_ops"].values()), ms
    assert math.isfinite(ms["value"]) and ms["value"] > 0, ms
    assert math.isfinite(ms["vs_baseline"]) and ms["vs_baseline"] > 0, ms
    # the process-replica arm (docs/fleet.md "Process replicas") must
    # prove the out-of-process story end to end: a 1-process-replica
    # fleet bit-identical to in-process, a child SIGKILLED for real
    # mid-burst with zero lost accepted requests and a fresh child pid
    # in the victim slot, the victims' p99 TTFT inside its bound, and
    # the autoscaler ramp growing, shrinking back, and never flapping
    # — a silently-in-process arm would be a quiet isolation lie
    pr = [r for r in records
          if r.get("metric")
          == "serving_tiny_process_kill_goodput_tok_per_sec"][0]
    assert pr["identity_ok"] is True, pr
    assert pr["zero_lost"] is True, pr
    assert pr["num_lost_requests"] == 0, pr
    assert pr["num_failovers"] >= 1, pr
    assert pr["num_respawns"] >= 1, pr
    assert pr["child_pid_fresh"] is True, pr
    assert pr["num_accepted"] > 0, pr
    assert (pr["victim_p99_ttft_ticks"]
            <= pr["victim_p99_bound_ticks"]), pr
    assert pr["autoscale_peak_replicas"] > 1, pr
    assert pr["autoscale_num_spawned"] == pr["autoscale_num_retired"], pr
    assert pr["autoscale_flap_free"] is True, pr
    assert pr["status_counts"].get("finished", 0) > 0, pr
    assert math.isfinite(pr["vs_baseline"]) and pr["value"] > 0, pr
    # the disaggregation arm (docs/fleet.md "Disaggregated roles")
    # must prove the two-stage story: the specialist fleet beat the
    # colocated one on TTFT p99 at equal device count, the handoff
    # actually moved requests/bytes, decode specialists never
    # prefilled a fresh prompt, and the prefill-specialist kill lost
    # nothing — a silently-colocated arm would be a quiet latency lie
    dg = [r for r in records
          if r.get("metric") == "serving_tiny_disagg_ttft_p99_ticks"][0]
    assert dg["vs_baseline"] < 1.0, dg
    assert dg["value"] < dg["colocated_ttft_p99_ticks"], dg
    assert dg["num_handoffs"] >= 1, dg
    assert dg["num_handoff_requests"] >= 1, dg
    assert dg["num_handoff_bytes"] > 0, dg
    assert dg["num_affinity_probes_skipped"] >= 1, dg
    assert (dg["decode_specialist_prefill_chunks"]
            <= dg["decode_specialist_imports"]), dg
    assert dg["zero_lost"] is True, dg
    assert dg["kill_num_failovers"] >= 1, dg
    assert dg["kill_num_lost_requests"] == 0, dg
    assert dg["status_counts"].get("finished", 0) > 0, dg
    assert dg["allocator_integrity_ok"] is True, dg
    assert math.isfinite(dg["vs_baseline"]) and dg["value"] > 0, dg
    # the shared-prefix-tier arm (docs/fleet.md "Shared prefix tier")
    # must prove the fleet-global cache story: the shared arm beat
    # the per-replica arm's fleet-wide hit rate AND steady-state TTFT
    # p99 at equal total spill bytes, dedupe/publish/hit all moved,
    # outputs stayed token-identical across arms, and the mid-trace
    # replica kill lost nothing — a tier that never dedupes or never
    # serves a fleet-wide hit would be a quiet capacity lie
    sp = [r for r in records
          if r.get("metric")
          == "serving_tiny_shared_prefix_fleet_hit_rate"][0]
    assert sp["vs_baseline"] < 1.0, sp
    assert sp["value"] > sp["per_replica_hit_rate"], sp
    assert (sp["shared_steady_ttft_p99_ticks"]
            < sp["per_replica_steady_ttft_p99_ticks"]), sp
    assert sp["num_shared_publishes"] >= 1, sp
    assert sp["num_shared_dedupe"] >= 1, sp
    assert sp["shared_tier_hits"] >= 1, sp
    assert sp["tokens_identical_across_arms"] is True, sp
    assert sp["zero_lost"] is True, sp
    assert sp["kill_num_failovers"] >= 1, sp
    assert sp["kill_num_lost_requests"] == 0, sp
    assert sp["status_counts"].get("finished", 0) > 0, sp
    assert sp["allocator_integrity_ok"] is True, sp
    assert math.isfinite(sp["vs_baseline"]) and sp["value"] > 0, sp
    # the sharded-train arm (docs/training.md "Sharded training") must
    # prove the 3D-parallel promotion story: mesh-arm losses certified
    # against meshless, compile counts pinned at ONE per arm (the spec-
    # canonicalization retrace gate), the collective contract audited
    # from AOT HLO (zero all-to-all; donation aliases cover every
    # sharded leaf), and the ZeRO shard bytes actually falling at
    # flat_world=2 — a silently-replicated arm would be a quiet
    # memory-scaling lie
    tsh = [r for r in records
           if r.get("metric") == "train_tiny_sharded_steps_per_sec"][0]
    assert tsh["loss_certified"] is True, tsh
    assert tsh["arms"]["meshless"]["steps_per_sec"] > 0, tsh
    for arm_name in ("mesh_1x2", "mesh_2x2"):
        arm = tsh["arms"][arm_name]
        assert arm["steps_per_sec"] > 0, tsh
        assert arm["compiles"] == 1, tsh
        assert arm["collective_ops"].get("all-to-all", 0) == 0, tsh
        assert arm["collective_ops"].get("collective-permute", 0) == 0, tsh
        assert arm["alias_pairs"] >= arm["sharded_leaves"] > 0, tsh
    assert tsh["arms"]["mesh_2x2"]["flat_world"] == 2, tsh
    assert (tsh["arms"]["mesh_2x2"]["opt_state_bytes_per_shard"]
            < tsh["arms"]["mesh_1x2"]["opt_state_bytes_per_shard"]), tsh
    assert tsh["opt_state_bytes_ratio"] > 1.0, tsh
    assert math.isfinite(tsh["value"]) and tsh["value"] > 0, tsh
    assert math.isfinite(tsh["vs_baseline"]) and tsh["vs_baseline"] > 0
    # the observability pipeline arm (docs/observability.md) certifies
    # dump -> trace_summary end to end AND re-checks zero perturbation
    ob = [r for r in records
          if r.get("metric") == "obs_pipeline_smoke_requests_summarized"][0]
    assert ob["bit_identical_with_observer"] is True, ob
    assert ob["trace_events"] > 0 and ob["recorder_events"] > 0, ob
    assert ob["ttft_observed"] == ob["value"], ob
    assert ob["summary_lines"] > 0, ob
    # every section also leaves a wall-time/exit-status record, so a
    # section that dies is a visible "failed" entry in the artifact,
    # never just an absence
    sections = {r["section"]: r for r in records if "section" in r}
    assert set(sections) == {
        "bench_layer_norm", "bench_fused_lamb", "bench_ddp_scaling",
        "bench_serving", "bench_serving_multistep",
        "bench_serving_speculative", "bench_serving_overload",
        "bench_serving_multitenant", "bench_serving_kv_memory",
        "bench_weight_quant",
        "bench_serving_fleet", "bench_serving_integrity",
        "bench_serving_mesh", "bench_serving_process",
        "bench_serving_disagg", "bench_serving_shared_prefix",
        "bench_train_step", "bench_train_sharded",
        "bench_obs_pipeline",
    }
    for rec in sections.values():
        assert rec["status"] == "ok", rec
        assert rec["wall_time_s"] > 0
