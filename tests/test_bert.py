"""Flagship model smoke tests (BASELINE configs[4] shape, tiny sizes)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.amp as amp
from apex_tpu.models import BertConfig, BertForPreTraining, pretraining_loss
from apex_tpu.optimizers import FusedLAMB


def _batch(cfg, B=2, S=16, seed=0):
    rng = np.random.RandomState(seed)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)))
    types = jnp.asarray(rng.randint(0, 2, (B, S)))
    mask = jnp.ones((B, S), jnp.int32).at[:, -3:].set(0)
    mlm_labels = jnp.asarray(
        np.where(rng.rand(B, S) < 0.15, rng.randint(0, cfg.vocab_size, (B, S)), -1))
    nsp = jnp.asarray(rng.randint(0, 2, (B,)))
    return ids, types, mask, mlm_labels, nsp


@pytest.mark.slow
def test_forward_shapes():
    cfg = BertConfig.tiny()
    model = BertForPreTraining(cfg)
    ids, types, mask, _, _ = _batch(cfg)
    params = model.init(jax.random.PRNGKey(0), ids, types, mask)
    mlm, nsp = model.apply(params, ids, types, mask)
    assert mlm.shape == (2, 16, cfg.vocab_size)
    assert nsp.shape == (2, 2)


@pytest.mark.slow
def test_bf16_training_step_with_amp_o2_and_lamb():
    """The north-star recipe at tiny scale: amp O2 + FusedLAMB."""
    cfg = BertConfig.tiny(dtype=jnp.bfloat16)
    model = BertForPreTraining(cfg)
    ids, types, mask, mlm_labels, nsp = _batch(cfg)
    params = model.init(jax.random.PRNGKey(0), ids, types, mask)["params"]

    opt = FusedLAMB(lr=1e-3)
    params, opt, handle = amp.initialize(params, opt, opt_level="O2", verbosity=0)
    # O2: dense kernels bf16, LN params fp32, masters on
    assert params["bert"]["layer_0"]["attention"]["q"]["kernel"].dtype == jnp.bfloat16
    assert params["bert"]["layer_0"]["attention_ln"]["scale"].dtype == jnp.float32
    assert opt.master_weights
    ost = opt.init(params)
    sst = handle.init_state()

    @jax.jit
    def step(p, ost, sst):
        def loss_fn(q):
            mlm, nspl = model.apply({"params": q}, ids, types, mask)
            return pretraining_loss(mlm, nspl, mlm_labels, nsp)

        (loss, found), grads = handle.value_and_grad(loss_fn, sst)(p)
        p2, ost2 = opt.step(grads, ost, p, skip_if=found)
        return p2, ost2, handle.scalers[0].update(sst, found), loss

    losses = []
    for _ in range(8):
        params, ost, sst, loss = step(params, ost, sst)
        losses.append(float(loss))
    assert int(ost.step) == 8
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_attention_mask_zeroes_padded_attention():
    cfg = BertConfig.tiny()
    model = BertForPreTraining(cfg)
    ids, types, mask, _, _ = _batch(cfg)
    params = model.init(jax.random.PRNGKey(0), ids, types, mask)
    # outputs at non-pad positions must not depend on pad-position ids
    mlm1, _ = model.apply(params, ids, types, mask)
    ids2 = ids.at[:, -1].set((ids[:, -1] + 7) % cfg.vocab_size)
    mlm2, _ = model.apply(params, ids2, types, mask)
    np.testing.assert_allclose(np.asarray(mlm1[:, :-3]), np.asarray(mlm2[:, :-3]),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_dropout_rng_and_determinism():
    cfg = BertConfig.tiny()
    model = BertForPreTraining(cfg)
    ids, types, mask, _, _ = _batch(cfg)
    params = model.init(jax.random.PRNGKey(0), ids, types, mask)
    a1, _ = model.apply(params, ids, types, mask, deterministic=False,
                        rngs={"dropout": jax.random.PRNGKey(1)})
    a2, _ = model.apply(params, ids, types, mask, deterministic=False,
                        rngs={"dropout": jax.random.PRNGKey(1)})
    a3, _ = model.apply(params, ids, types, mask, deterministic=False,
                        rngs={"dropout": jax.random.PRNGKey(2)})
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))
    assert not np.allclose(np.asarray(a1), np.asarray(a3))


@pytest.mark.slow
def test_gathered_mlm_head_matches_full_sequence_loss():
    """MLPerf gathered-predictions head (masked_positions): running the
    MLM transform+decoder only on the gathered positions must give the
    SAME pretraining loss as the full-sequence head with -1-ignored
    labels at the same positions (round-4 tail optimization)."""
    from apex_tpu.models import pretraining_loss

    cfg = BertConfig.tiny()
    model = BertForPreTraining(cfg)
    ids, types, mask, _, _ = _batch(cfg)
    B, S = ids.shape
    params = model.init(jax.random.PRNGKey(0), ids, types, mask)

    rng = np.random.RandomState(5)
    P = 4
    pos = np.stack([np.sort(rng.choice(S, P, replace=False))
                    for _ in range(B)])
    lab = rng.randint(0, cfg.vocab_size, (B, P))
    # full-sequence labels: -1 everywhere except the chosen positions
    full_lab = np.full((B, S), -1, np.int64)
    for b in range(B):
        full_lab[b, pos[b]] = lab[b]
    nsp_labels = jnp.asarray(rng.randint(0, 2, (B,)))

    mlm_full, nsp = model.apply(params, ids, types, mask)
    loss_full = pretraining_loss(mlm_full, nsp, jnp.asarray(full_lab),
                                 nsp_labels)

    mlm_g, nsp_g = model.apply(params, ids, types, mask,
                               masked_positions=jnp.asarray(pos))
    assert mlm_g.shape == (B, P, cfg.vocab_size)
    loss_g = pretraining_loss(mlm_g, nsp_g, jnp.asarray(lab), nsp_labels,
                              jnp.ones((B, P), jnp.float32))
    np.testing.assert_allclose(float(loss_g), float(loss_full),
                               rtol=1e-5, atol=1e-6)

    # padding slots (weight 0) must not change the loss
    pos_pad = np.concatenate([pos, np.zeros((B, 2), np.int64)], axis=1)
    lab_pad = np.concatenate([lab, np.zeros((B, 2), np.int64)], axis=1)
    w_pad = np.concatenate([np.ones((B, P), np.float32),
                            np.zeros((B, 2), np.float32)], axis=1)
    mlm_p, nsp_p = model.apply(params, ids, types, mask,
                               masked_positions=jnp.asarray(pos_pad))
    loss_p = pretraining_loss(mlm_p, nsp_p, jnp.asarray(lab_pad),
                              nsp_labels, jnp.asarray(w_pad))
    np.testing.assert_allclose(float(loss_p), float(loss_full),
                               rtol=1e-5, atol=1e-6)
