"""Memory scale-up tests (tier-1, CPU): quantized KV block storage
(int8/fp8 + per-row scales), the host-RAM spill tier for the prefix
cache, and the fused Pallas paged-read kernel — docs/serving.md
"memory tiers".

The certification layers:
- fp path untouched: quantization off + Pallas off is the PR 10
  engine, bit for bit (the existing serving/speculative/fault suites
  enforce that; here we pin the structural facts they rely on).
- quantized path: tolerance-certified against the fp path at the
  logits level, and DETERMINISTIC in itself — cross-K, preemption/
  resume, and snapshot/restore bit-identity all hold within a storage
  mode (position-keyed stochastic rounding).
- spill tier: a re-admitted block is token-identical to recompute,
  store contents stay disjoint from the device index, and the byte
  bound holds (check_integrity cross-checks both).
- Pallas read kernel: bit-identical to the XLA chain on the fp path
  (decode C == 1 included), tolerance-certified on the quantized path,
  in interpret mode.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import GPTConfig, GPTLMHeadModel
from apex_tpu.observability import Observability
from apex_tpu.ops.flash_attention import (
    FILL as _ATTN_FILL,
    paged_prefill_attention,
)
from apex_tpu.ops.multi_tensor import stochastic_round
from apex_tpu.ops.paged_attention_pallas import (
    FILL as _PALLAS_FILL,
    pallas_paged_read_wanted,
)
from apex_tpu.serving import (
    EngineConfig,
    HostSpillStore,
    InferenceEngine,
    KVCache,
    Request,
    SamplingParams,
    TenantQuota,
    TenantThrottledError,
)
from apex_tpu.serving.kv_cache import (
    BlockAllocator,
    copy_block,
    defragment,
    device_block_table,
    fp8_kv_dtype,
    kv_block_bytes,
    quantize_kv_rows,
    write_kv,
)

QUANT_MODES = ["int8"] + (["fp8"] if fp8_kv_dtype() is not None else [])


def _tiny_model():
    cfg = GPTConfig.tiny(dropout=0.0, remat=False)
    model = GPTLMHeadModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    return cfg, model, params


@pytest.fixture(scope="module")
def tiny():
    return _tiny_model()


def _requests(cfg, n=3, plen=12, new=6, sampled=False, seed=7,
              prefix=None, uid="r"):
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        prompt = list(prefix or []) + list(
            rng.randint(0, cfg.vocab_size, plen))
        out.append(Request(
            uid=f"{uid}{i}", prompt=prompt, max_new_tokens=new,
            sampling=(SamplingParams(temperature=1.0, top_k=40)
                      if sampled else SamplingParams())))
    return out


def _serve(tiny, ecfg, reqs):
    cfg, model, params = tiny
    eng = InferenceEngine(model, params, ecfg)
    for r in reqs:
        eng.add_request(dataclasses.replace(r))
    return eng, eng.run()


BASE = dict(max_batch=4, block_size=8, num_blocks=64,
            max_prefill_len=16, max_seq_len=48)


# ---------------------------------------------------------------------------
# quantization primitives
# ---------------------------------------------------------------------------

def test_stochastic_round_integer_targets_unbiased_and_clamped():
    x = jnp.asarray([0.3, -0.7, 126.9, -250.0, 300.0, 0.0])
    acc = np.zeros(len(x))
    n = 400
    for i in range(n):
        r = stochastic_round(x, jnp.int8, jax.random.PRNGKey(i))
        assert r.dtype == jnp.int8
        acc += np.asarray(r, np.float64)
    mean = acc / n
    # unbiased within the clamp range; clamped symmetric at +/-127
    assert abs(mean[0] - 0.3) < 0.1 and abs(mean[1] + 0.7) < 0.1
    assert 126.0 <= mean[2] <= 127.0
    assert mean[3] == -127.0 and mean[4] == 127.0 and mean[5] == 0.0
    # non-finite rounds to 0 for integer targets
    r = stochastic_round(jnp.asarray([jnp.inf, jnp.nan]), jnp.int8,
                         jax.random.PRNGKey(0))
    assert np.asarray(r).tolist() == [0, 0]


@pytest.mark.parametrize("mode", QUANT_MODES)
def test_quantize_kv_rows_roundtrip_bounded_and_deterministic(mode):
    vals = jax.random.normal(jax.random.PRNGKey(0), (2, 6, 3, 8)) * 3.0
    pos = jnp.tile(jnp.arange(6)[None], (2, 1))
    q1, s1 = quantize_kv_rows(vals, pos, mode)
    q2, s2 = quantize_kv_rows(vals, pos, mode)
    # deterministic: position-keyed rounding, no ambient randomness
    assert jnp.array_equal(q1, q2) and jnp.array_equal(s1, s2)
    deq = q1.astype(jnp.float32) * s1[..., None]
    err = jnp.abs(deq - vals.astype(jnp.float32))
    if mode == "int8":
        # absolute quantum: one int8 step = the row's scale
        assert bool(jnp.all(err <= s1[..., None] + 1e-7))
    else:
        # fp8 e4m3 keeps RELATIVE precision (3 mantissa bits, <= 2^-3
        # rounding error) down to the subnormal floor (one scale unit)
        bound = (jnp.abs(vals.astype(jnp.float32)) * 0.125
                 + s1[..., None] + 1e-7)
        assert bool(jnp.all(err <= bound))
    # an all-zero row stores scale 0 and dequantizes to exact zeros
    zq, zs = quantize_kv_rows(jnp.zeros((1, 2, 2, 4)),
                              jnp.zeros((1, 2), jnp.int32), mode)
    assert float(jnp.max(jnp.abs(zq.astype(jnp.float32)))) == 0.0
    assert float(jnp.max(jnp.abs(zs))) == 0.0


def test_quantize_same_position_same_rounding_different_elsewhere():
    """The rounding stream is a function of the ABSOLUTE position: the
    same row at the same position always rounds identically (the
    resume-determinism premise); a different position may not."""
    vals = jax.random.normal(jax.random.PRNGKey(1), (1, 1, 2, 16))
    q_a, _ = quantize_kv_rows(vals, jnp.asarray([[5]], jnp.int32), "int8")
    q_b, _ = quantize_kv_rows(vals, jnp.asarray([[5]], jnp.int32), "int8")
    q_c, _ = quantize_kv_rows(vals, jnp.asarray([[6]], jnp.int32), "int8")
    assert jnp.array_equal(q_a, q_b)
    assert not jnp.array_equal(q_a, q_c)   # fresh stream per position
    # distinct streams (write_kv tags each (layer, K/V) pair) draw
    # independent noise at the SAME position — correlated rounding
    # would compound one-directionally through the layers
    q_d, _ = quantize_kv_rows(vals, jnp.asarray([[5]], jnp.int32),
                              "int8", stream=1)
    assert not jnp.array_equal(q_a, q_d)


def test_write_kv_fp_path_is_plain_paged_write():
    """Quantization off: write_kv must produce the exact bytes the two
    paged_write calls produced (the fp bit-identity premise)."""
    from apex_tpu.serving.kv_cache import paged_write

    cache = KVCache.create(2, 8, 4, 2, 8, dtype=jnp.float32)
    assert cache.quantization is None and cache.k_scale is None
    tbl = device_block_table(np.array([[0, 1, -1]], np.int32), 8)
    pos = jnp.arange(6)[None]
    k = jax.random.normal(jax.random.PRNGKey(0), (1, 6, 2, 8))
    v = jax.random.normal(jax.random.PRNGKey(1), (1, 6, 2, 8))
    valid = jnp.ones((1, 6), bool)
    got = write_kv(cache, 1, tbl, pos, k, v, valid)
    want_k = paged_write(cache.k, 1, tbl, pos, k, valid)
    want_v = paged_write(cache.v, 1, tbl, pos, v, valid)
    assert jnp.array_equal(got.k, want_k)
    assert jnp.array_equal(got.v, want_v)
    assert got.k_scale is None and got.v_scale is None


@pytest.mark.parametrize("mode", QUANT_MODES)
def test_copy_block_and_defragment_move_scales(mode):
    """The CoW copy and the defrag permutation must carry a block's
    scales with its payload — a quantized block whose scales stay
    behind dequantizes the right bytes with the wrong scales."""
    cache = KVCache.create(2, 6, 4, 2, 8, quantization=mode)
    tbl = device_block_table(np.array([[4, 2, -1]], np.int32), 6)
    pos = jnp.arange(8)[None]
    k = jax.random.normal(jax.random.PRNGKey(0), (1, 8, 2, 8)) * 2.0
    v = jax.random.normal(jax.random.PRNGKey(1), (1, 8, 2, 8)) * 2.0
    cache = write_kv(cache, 0, tbl, pos, k, v, jnp.ones((1, 8), bool))

    def deq(c, b):
        return (c.k[0, b].astype(jnp.float32)
                * c.k_scale[0, b][..., None])

    src_vals = deq(cache, 4)
    copied = copy_block(cache, 4, 1)
    assert jnp.array_equal(deq(copied, 1), src_vals)
    assert jnp.array_equal(copied.k_scale[:, 1], cache.k_scale[:, 4])

    # defragment: blocks {4, 2} compact to {0, 1}; dequantized contents
    # must survive the permutation (scales moved with payload)
    alloc = BlockAllocator(6)
    ids = alloc.alloc(5)        # 0..4
    alloc.free([i for i in ids if i not in (4, 2)])
    tables = np.array([[4, 2, -1]], np.int32)
    new_cache, new_tables = defragment(cache, alloc, tables)
    b_new = int(new_tables[0, 0])
    assert jnp.array_equal(deq(new_cache, b_new), src_vals)


def test_kv_block_bytes_quantized_footprint():
    fp = kv_block_bytes(2, 8, 4, 16, dtype=jnp.float32)
    q8 = kv_block_bytes(2, 8, 4, 16, quantization="int8")
    # int8 payload is 1/4 the fp32 bytes; scales add 4B per (tok, head)
    assert q8 < fp / 2
    assert q8 == fp // 4 + 2 * 2 * 8 * 4 * 4


# ---------------------------------------------------------------------------
# the fused Pallas read kernel (interpret mode)
# ---------------------------------------------------------------------------

def _paged_setup(mode, seed=0):
    cache = KVCache.create(1, 8, 4, 2, 8, quantization=mode)
    tbl = jnp.asarray(np.array([[0, 1, 6, 8], [3, 2, 8, 8]], np.int32))
    tbl = jnp.where(tbl >= 0, tbl, 8)
    pos = jnp.tile(jnp.arange(10)[None], (2, 1))
    k = jax.random.normal(jax.random.PRNGKey(seed), (2, 10, 2, 8))
    v = jax.random.normal(jax.random.PRNGKey(seed + 1), (2, 10, 2, 8))
    valid = pos < jnp.asarray([[10], [7]])
    cache = write_kv(cache, 0, tbl, pos, k, v, valid)
    scales = ((None, None) if cache.k_scale is None
              else (cache.k_scale[0], cache.v_scale[0]))
    return cache, tbl, scales


def test_pallas_fill_matches_flash_attention_fill():
    assert _PALLAS_FILL == _ATTN_FILL


@pytest.mark.parametrize("mode", [None] + QUANT_MODES)
@pytest.mark.parametrize("chunk", [1, 3, 5])
def test_pallas_read_chain_equivalence_matrix(mode, chunk):
    """The Pallas-vs-XLA equivalence matrix (interpret mode): decode
    (C == 1, q_positions None), prefill-chunk, and verify-style reads,
    fp and quantized. fp is BIT-identical; quantized is certified to
    tight tolerance (and is observed bitwise on this backend)."""
    cache, tbl, (ks, vs) = _paged_setup(mode)
    ctx = jnp.asarray([10, 7], jnp.int32)
    q = jax.random.normal(jax.random.PRNGKey(2), (2, chunk, 2, 8))
    qpos = (None if chunk == 1 else
            jnp.tile(jnp.arange(10 - chunk, 10)[None], (2, 1)))

    def call(use_pallas):
        return paged_prefill_attention(
            q, cache.k[0], cache.v[0], tbl, qpos, ctx, 0.35,
            k_scales=ks, v_scales=vs, use_pallas=use_pallas)

    a, b = call(False), call(True)
    if mode is None:
        assert jnp.array_equal(a, b), (
            f"fp Pallas read must be bit-identical (C={chunk})")
    else:
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=0, atol=1e-6)

    # jitted (the engine's calling convention) — same contract
    fj = jax.jit(lambda q: paged_prefill_attention(
        q, cache.k[0], cache.v[0], tbl, qpos, ctx, 0.35,
        k_scales=ks, v_scales=vs, use_pallas=True))
    if mode is None:
        assert jnp.array_equal(a, fj(q))


def test_pallas_flag_env_and_kwarg(monkeypatch):
    monkeypatch.delenv("APEX_PAGED_ATTENTION_PALLAS", raising=False)
    assert pallas_paged_read_wanted(None) is False
    assert pallas_paged_read_wanted(True) is True
    monkeypatch.setenv("APEX_PAGED_ATTENTION_PALLAS", "1")
    assert pallas_paged_read_wanted(None) is True
    assert pallas_paged_read_wanted(False) is False
    monkeypatch.setenv("APEX_PAGED_ATTENTION_PALLAS", "0")
    assert pallas_paged_read_wanted(None) is False


@pytest.mark.parametrize("sampled", [False, True])
def test_pallas_engine_end_to_end_bit_identical(tiny, monkeypatch,
                                                sampled):
    """The whole engine (prefill + decode + prefix caching) with the
    fused read kernel produces the identical token streams — the env
    flag is read at trace time, so it must be set before the engine
    compiles its programs."""
    cfg, _, _ = tiny
    reqs = _requests(cfg, n=3, sampled=sampled)
    ecfg = EngineConfig(**BASE, enable_prefix_caching=True)
    monkeypatch.delenv("APEX_PAGED_ATTENTION_PALLAS", raising=False)
    _, base_out = _serve(tiny, ecfg, reqs)
    monkeypatch.setenv("APEX_PAGED_ATTENTION_PALLAS", "1")
    _, pallas_out = _serve(tiny, ecfg, reqs)
    assert pallas_out == base_out


# ---------------------------------------------------------------------------
# quantized engine: tolerance + determinism matrix
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", QUANT_MODES)
def test_quantized_prefill_logits_tolerance(tiny, mode):
    """End-to-end forward tolerance: the same prompt prefilled through
    a quantized cache must produce last-position logits close to the
    fp-cache forward — the quantization error budget surfaced at the
    only place the engine consumes the cache."""
    cfg, model, params = tiny

    def logits_with(quantization):
        cache = KVCache.create(
            cfg.num_layers, 16, 8, cfg.num_heads,
            cfg.hidden_size // cfg.num_heads, dtype=jnp.float32,
            quantization=quantization)
        ids = jnp.asarray(
            np.random.RandomState(3).randint(0, cfg.vocab_size, (1, 16)))
        tbl = device_block_table(np.array([[0, 1, -1]], np.int32), 16)
        out, _ = model.apply(
            params, ids, deterministic=True, kv_cache=cache,
            block_tables=tbl,
            cache_positions=jnp.arange(16)[None],
            seq_lens=jnp.asarray([16], jnp.int32),
            write_start=jnp.asarray([0], jnp.int32))
        return out[0, -1]

    fp = logits_with(None)
    quant = logits_with(mode)
    # loose enough for int8 end-to-end through every layer, tight
    # enough that a scale/payload mismatch (wrong block, stale scale)
    # fails by orders of magnitude
    np.testing.assert_allclose(np.asarray(quant), np.asarray(fp),
                               rtol=0.15, atol=0.15)


@pytest.mark.parametrize("sampled", [False, True])
def test_quantized_outputs_identical_across_decode_steps(tiny, sampled):
    cfg, _, _ = tiny
    reqs = _requests(cfg, sampled=sampled)
    outs = [_serve(tiny, EngineConfig(**BASE, kv_quantization="int8",
                                      decode_steps=k), reqs)[1]
            for k in (1, 4)]
    assert outs[0] == outs[1]


def test_quantized_preemption_resume_deterministic(tiny):
    """Tight pool forces preemption + cached resume; the re-prefill
    re-quantizes the history bit-identically (position-keyed
    rounding), so outputs equal the roomy-pool run's."""
    cfg, _, _ = tiny
    reqs = _requests(cfg, n=4, plen=12, new=8, sampled=True)
    roomy = EngineConfig(**BASE, kv_quantization="int8",
                         enable_prefix_caching=True)
    tight = dataclasses.replace(roomy, num_blocks=7, max_batch=3)
    eng_r, out_r = _serve(tiny, roomy, reqs)
    eng_t, out_t = _serve(tiny, tight, reqs)
    assert eng_t.stats()["num_preemptions"] > 0
    assert out_t == out_r


@pytest.mark.parametrize("spec", [0, 4])
def test_quantized_snapshot_restore_bit_identical(tiny, spec):
    cfg, model, params = tiny
    ecfg = EngineConfig(**BASE, kv_quantization="int8",
                        spec_tokens=spec)
    reqs = _requests(cfg, n=3, plen=10, new=8, sampled=True)
    _, uninterrupted = _serve(tiny, ecfg, reqs)

    eng = InferenceEngine(model, params, ecfg)
    for r in reqs:
        eng.add_request(dataclasses.replace(r))
    for _ in range(3):
        eng.step()
    snap = eng.snapshot()
    fresh = InferenceEngine(model, params, ecfg)
    fresh.restore(snap)
    out = dict(snap["finished"])
    out.update(fresh.run())
    assert out == uninterrupted


def test_quantized_greedy_speculative_matches_plain(tiny):
    """Greedy spec-vs-not bit-identity is structural (argmax equality)
    and survives quantization: the verify forward reads the same
    quantized cache the scan would."""
    cfg, _, _ = tiny
    reqs = _requests(cfg, n=3, plen=12, new=8, sampled=False)
    _, plain = _serve(tiny, EngineConfig(**BASE, kv_quantization="int8"),
                      reqs)
    _, spec = _serve(tiny, EngineConfig(**BASE, kv_quantization="int8",
                                        spec_tokens=4), reqs)
    assert spec == plain


def test_quantized_block_charges_reduced_footprint(tiny):
    """The tenant ledger denominates in full-precision block units: a
    request the fp ledger throttles at the door fits under int8 (its
    worst case charges block_weight < 1 per block)."""
    cfg, model, params = tiny
    quotas = {"t": TenantQuota(max_resident_blocks=2)}
    req = Request(uid="q0", prompt=list(range(1, 17)), max_new_tokens=8,
                  tenant="t")   # 24 tokens = 3 blocks worst case
    fp_eng = InferenceEngine(model, params, EngineConfig(
        **BASE, tenant_quotas=quotas))
    with pytest.raises(TenantThrottledError):
        fp_eng.add_request(dataclasses.replace(req))
    q_eng = InferenceEngine(model, params, EngineConfig(
        **BASE, kv_quantization="int8", tenant_quotas=quotas))
    assert q_eng._block_weight < 0.7
    q_eng.add_request(dataclasses.replace(req))
    out = q_eng.run()
    assert len(out["q0"]) == 8
    q_eng.check_allocator_integrity()


def test_kv_quantization_config_validation(tiny):
    with pytest.raises(ValueError, match="kv_quantization"):
        EngineConfig(**BASE, kv_quantization="int4")
    # fp engine keeps a scale-less pool and zeroed spill stats
    cfg, model, params = tiny
    eng = InferenceEngine(model, params, EngineConfig(**BASE))
    assert eng.cache.k_scale is None
    st = eng.stats()
    assert st["spill_blocks"] == 0 and st["spill_hit_rate"] == 0.0


# ---------------------------------------------------------------------------
# the host-RAM spill tier
# ---------------------------------------------------------------------------

def _spill_cfg(**kw):
    base = dict(max_batch=2, block_size=8, num_blocks=8,
                max_prefill_len=16, max_seq_len=32,
                enable_prefix_caching=True, spill_max_bytes=10_000_000)
    base.update(kw)
    return EngineConfig(**base)


def _distinct_prompts(cfg, n=4, plen=16, seed=3):
    rng = np.random.RandomState(seed)
    return [list(rng.randint(0, cfg.vocab_size, plen)) for _ in range(n)]


def _serve_prompts(eng, prompts, tag, new=4):
    for i, p in enumerate(prompts):
        eng.add_request(Request(uid=f"{tag}{i}", prompt=p,
                                max_new_tokens=new))
    return eng.run()


@pytest.mark.parametrize("quant", [None, "int8"])
def test_spill_readmit_token_identical_vs_recompute(tiny, quant):
    """The core spill cert: flush the prefix cache into the host tier,
    re-serve the same prompts, and the upload-re-admitted run must be
    TOKEN-IDENTICAL to the recompute run of a spill-less engine."""
    cfg, model, params = tiny
    prompts = _distinct_prompts(cfg)

    def serve_twice(spill_bytes):
        kw = dict(kv_quantization=quant)
        if spill_bytes is None:
            base = dict(max_batch=2, block_size=8, num_blocks=8,
                        max_prefill_len=16, max_seq_len=32,
                        enable_prefix_caching=True, **kw)
            eng = InferenceEngine(model, params, EngineConfig(**base))
        else:
            eng = InferenceEngine(model, params,
                                  _spill_cfg(spill_max_bytes=spill_bytes,
                                             **kw))
        o1 = _serve_prompts(eng, prompts, "a")
        eng.allocator.flush_evictable()   # rung-2's call: all -> spill
        o2 = _serve_prompts(eng, prompts, "b")
        return eng, o1, o2

    spill_eng, s1, s2 = serve_twice(10_000_000)
    none_eng, n1, n2 = serve_twice(None)
    assert (s1, s2) == (n1, n2)
    st = spill_eng.stats()
    assert st["num_blocks_spilled"] > 0
    assert st["spill_hits"] > 0 and st["spill_hit_rate"] > 0
    assert none_eng.stats()["spill_hits"] == 0
    spill_eng.check_allocator_integrity()


def test_spill_store_lru_byte_bound():
    store = HostSpillStore(max_bytes=1000)
    blk = {"k": np.zeros((1, 8, 2, 4), np.int8),
           "v": np.zeros((1, 8, 2, 4), np.int8)}     # 128 B
    for i in range(10):
        store.put(f"h{i}", dict(blk))
    assert store.total_bytes <= 1000
    assert len(store) == 7 and store.evictions == 3
    assert "h0" not in store and "h9" in store       # LRU dropped first
    # an entry bigger than the whole bound is refused, counted
    big = {"k": np.zeros((4, 64, 8, 8), np.float32), "v": None}
    assert store.put("huge", big) is False
    assert "huge" not in store
    # pop removes; discard tolerates absence
    assert store.pop("h9") is not None and store.pop("h9") is None
    store.discard("h9")
    with pytest.raises(ValueError):
        HostSpillStore(max_bytes=0)


def test_spill_integrity_cross_check(tiny):
    """check_integrity must reject a hash both device-indexed and
    spilled, and a store over its byte bound — the new tier rides
    engine.check_allocator_integrity()."""
    cfg, model, params = tiny
    eng = InferenceEngine(model, params, _spill_cfg())
    prompts = _distinct_prompts(cfg, n=2)
    _serve_prompts(eng, prompts, "a")
    eng.allocator.flush_evictable()
    _serve_prompts(eng, prompts, "b")
    eng.check_allocator_integrity()     # healthy churn passes
    # violate disjointness: copy a device-indexed hash into the store
    live_hash = next(iter(eng.allocator._hash_to_block))
    eng.spill.put(live_hash, {"k": np.zeros(4, np.int8),
                              "v": np.zeros(4, np.int8)})
    with pytest.raises(ValueError, match="device-indexed and spilled"):
        eng.check_allocator_integrity()
    eng.spill.discard(live_hash)
    eng.check_allocator_integrity()
    # violate the byte bound behind the store's back
    eng.spill.max_bytes = -1
    eng.spill.total_bytes = 5
    with pytest.raises(ValueError, match="over its"):
        eng.check_allocator_integrity()


def test_spill_snapshot_audit_only_and_cross_restore(tiny):
    """Spill state is audit-only: the snapshot carries a 'spill'
    section restore() never reads, the fingerprint excludes the knob,
    and a snapshot from a spill engine restores bit-identically into
    an engine WITHOUT the tier (and vice versa)."""
    cfg, model, params = tiny
    spill_cfg = _spill_cfg()
    plain_cfg = dataclasses.replace(spill_cfg, spill_max_bytes=None)
    reqs = _requests(cfg, n=3, plen=10, new=6, sampled=True, seed=5)

    def interrupted(build_cfg, restore_cfg):
        eng = InferenceEngine(model, params, build_cfg)
        for r in reqs:
            eng.add_request(dataclasses.replace(r))
        for _ in range(3):
            eng.step()
        snap = eng.snapshot()
        if build_cfg.spill_max_bytes is not None:
            assert snap["spill"]["audit_only"] is True
        fresh = InferenceEngine(model, params, restore_cfg)
        fresh.restore(snap)
        out = dict(snap["finished"])
        out.update(fresh.run())
        return out

    _, uninterrupted = _serve(tiny, plain_cfg, reqs)
    assert interrupted(spill_cfg, plain_cfg) == uninterrupted
    assert interrupted(plain_cfg, spill_cfg) == uninterrupted


def test_spill_recorder_events_and_trace_summary(tiny, tmp_path):
    """The flight recorder narrates the tier (spill + spill_upload are
    vocabulary now) and tools/trace_summary.py reports them."""
    import importlib.util
    import json as _json
    import pathlib

    cfg, model, params = tiny
    obs = Observability()
    eng = InferenceEngine(model, params, _spill_cfg(), obs=obs)
    prompts = _distinct_prompts(cfg, n=2)
    _serve_prompts(eng, prompts, "a")
    eng.allocator.flush_evictable()
    _serve_prompts(eng, prompts, "b")
    kinds = {e["kind"] for e in obs.recorder.tail()}
    assert "spill" in kinds and "spill_upload" in kinds

    dump_path = tmp_path / "dump.json"
    with open(dump_path, "w") as f:
        _json.dump(obs.dump(), f)
    spec = importlib.util.spec_from_file_location(
        "_ts", pathlib.Path(__file__).resolve().parents[1]
        / "tools" / "trace_summary.py")
    ts = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ts)
    report = ts.summarize_file(str(dump_path))
    assert "spill tier" in report


def test_spill_config_validation():
    with pytest.raises(ValueError, match="enable_prefix_caching"):
        EngineConfig(**BASE, spill_max_bytes=1000)
    with pytest.raises(ValueError, match="spill_max_bytes"):
        EngineConfig(**BASE, enable_prefix_caching=True,
                     spill_max_bytes=0)
