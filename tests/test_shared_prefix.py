"""Fleet-global shared prefix tier certification (tier-1, CPU): the
ISSUE 18 layer (docs/fleet.md, "Shared prefix tier").

The :class:`SharedPrefixStore` unit contracts — content-addressed
refcounted dedupe (one copy, publisher shares audited by
``check_integrity``), byte-budget LRU eviction with the side tables
kept consistent, corrupt-entry discard on fetch and on the
round-robin scrub, fractional per-tenant attribution — and the
router-level certs: a shared-tier hit is token-identical to recompute
(fp + int8, greedy + sampled, speculation on/off), a corrupt shared
entry is discarded and served by recompute token-identically, the
tier off is bit-identical run-to-run under a constant clock with
every shared counter reading zero, process replicas publish/probe/
fetch over the framed RPC wire (torn frames retried, nothing lost),
drain-and-migrate and the SDC cross-check compose with the tier, and
the placement hot path's one-chain-hash-walk-per-decision bound stays
pinned (``num_hash_walks``)."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import GPTConfig, GPTLMHeadModel
from apex_tpu.observability import Observability
from apex_tpu.serving import (
    EngineConfig,
    FleetConfig,
    FleetRouter,
    Request,
    SamplingParams,
    SharedPrefixStore,
)
from apex_tpu.serving.process_replica import gpt_model_spec
from apex_tpu.utils.faults import FaultPlan, FaultSpec


@pytest.fixture(scope="module")
def tiny_gpt():
    cfg = GPTConfig.tiny(dropout=0.0, remat=False)
    model = GPTLMHeadModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    return cfg, model, params


BLK = 4096   # comfortably above one tiny-model block payload

# the proven shared-tier physics (bench_serving_shared_prefix): a
# pool small enough that finished prompts EVICT into the local spill
# tier (num_blocks=8 = one full 32-token sequence), a local tier big
# enough to hold a whole seeded 7-block run (8 blocks — a run larger
# than its landing tier evicts its own head before _admit sees it),
# and 28-token prompts so one prompt is 7 chain blocks
SMALL_KW = dict(max_batch=2, block_size=4, num_blocks=8,
                max_prefill_len=8, max_seq_len=32, seed=11,
                enable_prefix_caching=True, max_waiting=64,
                snapshot_interval_ticks=2, spill_max_bytes=8 * BLK)
SHARED_FLEET_KW = dict(affinity_weight=0.0,       # affinity-BLIND
                       shared_prefix_bytes=60 * BLK)


def _fleet(tiny_gpt, n=2, fleet_kw=None, clock=None, faults=None,
           obs=None, process=False, **overrides):
    cfg, model, params = tiny_gpt
    kw = dict(SMALL_KW)
    kw.update(overrides)
    fkw = dict(fleet_kw or {})
    extra = {}
    if process:
        fkw.setdefault("replica_mode", "process")
        fkw.setdefault("rpc_timeout_s", 60.0)
        extra["model_spec"] = gpt_model_spec(cfg)
    return FleetRouter(model, params, EngineConfig(**kw),
                       FleetConfig(num_replicas=n, **fkw),
                       clock=clock, faults=faults, obs=obs, **extra)


def _warm_trace(n=12, npref=3, sampled=False, new=4, seed=17,
                uid="w", tenant=None):
    """``n`` requests cycling over ``npref`` distinct 28-token
    prompts (7 chain blocks each). npref is ODD on purpose: paired
    placement on two replicas alternates, and an even prefix count
    would partition the prefixes perfectly by replica parity — every
    request a LOCAL hit, nothing for the shared tier to prove."""
    assert npref % 2 == 1
    rng = np.random.RandomState(seed)
    prefixes = [list(rng.randint(1, 50, 28)) for _ in range(npref)]
    out = []
    for k in range(n):
        samp = (SamplingParams(temperature=1.0, top_k=10)
                if sampled else SamplingParams())
        out.append(Request(f"{uid}{k}", list(prefixes[k % npref]),
                           max_new_tokens=new, sampling=samp,
                           **({"tenant": tenant(k)} if tenant else {})))
    return out


def _drive_pairs(fleet, reqs):
    """Submit in pairs and DRAIN between pairs — the load pattern the
    seed-at-placement tier is built for: evictions from finished pairs
    publish before the next placement probes."""
    for k in range(0, len(reqs), 2):
        for r in reqs[k:k + 2]:
            fleet.add_request(r)
        while fleet.has_work:
            fleet.step()
    return fleet.run(return_status=True)


def _resdict(res):
    return {u: (tuple(r.tokens), r.status) for u, r in res.items()}


def _payload(seed, nbytes=1024):
    rng = np.random.RandomState(seed)
    half = nbytes // 2
    return {"k": rng.randint(0, 127, half).astype(np.int8),
            "v": rng.randint(0, 127, half).astype(np.int8)}


# ---------------------------------------------------------------------------
# SharedPrefixStore units: dedupe, LRU budget, attribution, audit
# ---------------------------------------------------------------------------


def test_store_dedupe_is_refcounted_and_audited():
    store = SharedPrefixStore(1 << 20)
    assert store.publish("h0", _payload(0), tenant="a") is True
    bytes_one = store.total_bytes
    # the same hash from two more publishers: references, not bytes
    assert store.publish("h0", None, tenant="b") is True
    assert store.publish("h0", _payload(0), tenant="a") is True
    assert len(store) == 1
    assert store.total_bytes == bytes_one
    assert store.dedupe_hits == 2
    assert store._refs["h0"] == 3
    assert store._owners["h0"] == {"a": 2, "b": 1}
    store.check_integrity()
    # a payload-less publish of a NON-resident hash cannot store
    assert store.publish("h1", None, tenant="a") is False
    assert "h1" not in store
    st = store.stats()
    assert st["blocks"] == 1 and st["dedupe_hits"] == 2


def test_store_byte_budget_lru_keeps_side_tables_consistent():
    store = SharedPrefixStore(3 * 1024)
    for k in range(4):
        assert store.publish(f"h{k}", _payload(k)) is True
    # h0 fell off the LRU end; its refcount/ownership rows went with it
    assert "h0" not in store and store.evictions == 1
    assert len(store) == 3 and store.total_bytes == 3 * 1024
    assert set(store._refs) == set(store._owners) == {"h1", "h2", "h3"}
    store.check_integrity()
    # probe: contiguous resident run only, honoring start
    assert store.probe(["h1", "h2", "h3"]) == 3
    assert store.probe(["h0", "h1"]) == 0
    assert store.probe(["h1", "hX", "h2"]) == 1
    assert store.probe(["h0", "h1", "h2"], start=1) == 2
    # a dedupe publish refreshes recency: h1 survives the next insert
    assert store.publish("h1", None) is True
    assert store.publish("h4", _payload(4)) is True
    assert "h1" in store and "h2" not in store
    store.check_integrity()
    # an entry over the whole budget is refused, never resident
    assert SharedPrefixStore(100).publish("big", _payload(9)) is False
    small = SharedPrefixStore(100)
    small.publish("big", _payload(9))
    assert small.refused == 1 and len(small) == 0


def test_store_tenant_bytes_split_by_publisher_share():
    store = SharedPrefixStore(1 << 20)
    store.publish("h", _payload(3), tenant="a")
    store.publish("h", None, tenant="b")
    store.publish("h", None, tenant="a")
    tb = store.tenant_bytes()
    assert tb["a"] == pytest.approx(1024 * 2 / 3, abs=1e-3)
    assert tb["b"] == pytest.approx(1024 * 1 / 3, abs=1e-3)
    assert sum(tb.values()) == pytest.approx(store.total_bytes,
                                             abs=1e-3)


def test_store_check_integrity_catches_ledger_violations():
    store = SharedPrefixStore(1 << 20)
    store.publish("h", _payload(1))
    store._refs["h"] = 0
    with pytest.raises(ValueError, match="refcount"):
        store.check_integrity()
    store._refs["h"] = 1
    store._owners["stray"] = {"a": 1}
    with pytest.raises(ValueError, match="out of sync"):
        store.check_integrity()
    del store._owners["stray"]
    store._owners["h"] = {"a": 2}
    with pytest.raises(ValueError, match="sum to its refcount"):
        store.check_integrity()


# ---------------------------------------------------------------------------
# SharedPrefixStore units: corruption discard (fetch + scrub)
# ---------------------------------------------------------------------------


def test_store_fetch_discards_corrupt_entry_with_references():
    hits = []
    store = SharedPrefixStore(1 << 20,
                              on_corrupt=lambda s, h: hits.append((s, h)))
    store.publish("h", _payload(5), tenant="a")
    store.publish("h", None, tenant="b")
    # host-RAM rot: flip a stored byte AFTER the put-time checksum
    store._entries["h"]["payload"]["k"].view(np.uint8)[0] ^= 0xFF
    assert store.fetch("h") is None
    assert store.corrupt_discards == 1
    assert hits == [("spill_get", "h")]
    # discarded WITH its references — a reference is attribution,
    # not a pin — and the ledger still audits clean
    assert "h" not in store and "h" not in store._refs
    store.check_integrity()
    # a fresh publish of the same hash stores clean bytes again
    assert store.publish("h", _payload(5), tenant="a") is True
    assert store.fetch("h") is not None


def test_store_scrub_round_robin_finds_cold_rot():
    hits = []
    store = SharedPrefixStore(1 << 20,
                              on_corrupt=lambda s, h: hits.append((s, h)))
    for k in range(3):
        store.publish(f"h{k}", _payload(k))
    store._entries["h1"]["payload"]["v"].view(np.uint8)[0] ^= 0xFF
    # two budgeted passes cover all three entries round-robin
    v0, c0 = store.scrub(2)
    v1, c1 = store.scrub(2)
    assert v0 + v1 >= 3 and c0 + c1 == 1
    assert "h1" not in store and len(store) == 2
    assert ("scrub", "h1") in hits
    store.check_integrity()


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------


def test_shared_tier_config_validation(tiny_gpt):
    cfg, model, params = tiny_gpt
    with pytest.raises(ValueError, match="shared_prefix_bytes"):
        FleetConfig(shared_prefix_bytes=0)
    with pytest.raises(ValueError, match="shared_scrub_blocks"):
        FleetConfig(shared_scrub_blocks=-1)
    with pytest.raises(ValueError, match="max_bytes"):
        SharedPrefixStore(0)
    kw = dict(SMALL_KW, enable_prefix_caching=False)
    kw.pop("spill_max_bytes")
    with pytest.raises(ValueError, match="enable_prefix_caching"):
        FleetRouter(model, params, EngineConfig(**kw),
                    FleetConfig(num_replicas=1,
                                shared_prefix_bytes=1 << 20))


# ---------------------------------------------------------------------------
# the hit cert: shared-tier hit token-identical to recompute
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("sampled,spec_tokens,quant", [
    (False, 0, None),
    (True, 0, None),
    (False, 3, None),
    (True, 3, None),
    (False, 0, "int8"),
])
def test_shared_hit_token_identical_to_recompute(tiny_gpt, sampled,
                                                 spec_tokens, quant):
    """The tier's whole contract: with the shared tier ON (and
    genuinely hitting — publishes, dedupe and seeded hits all
    nonzero), every request's tokens and status are IDENTICAL to the
    tier-off fleet that recomputes everything. fp + int8, greedy +
    sampled, speculation on/off."""
    overrides = dict(spec_tokens=spec_tokens)
    if quant is not None:
        overrides["kv_quantization"] = quant
    outs = {}
    for arm, fkw in (("off", dict(affinity_weight=0.0)),
                     ("on", dict(SHARED_FLEET_KW))):
        fleet = _fleet(tiny_gpt, n=2, fleet_kw=fkw, **overrides)
        res = _drive_pairs(fleet, _warm_trace(n=12, sampled=sampled))
        outs[arm] = _resdict(res)
        st = fleet.stats()
        assert st["num_lost_requests"] == 0
        if arm == "on":
            assert st["num_shared_publishes"] >= 1, st
            assert st["num_shared_dedupe"] >= 1, st
            assert st["shared_tier_hits"] >= 1, st
            assert st["num_shared_corrupt_discards"] == 0, st
            fleet._shared.check_integrity()
        else:
            for k in ("shared_tier_blocks", "shared_tier_bytes",
                      "shared_tier_hits", "num_shared_publishes",
                      "num_shared_dedupe", "num_shared_evictions",
                      "num_shared_refused",
                      "num_shared_corrupt_discards",
                      "num_shared_scrub_blocks_verified"):
                assert st[k] == 0, (k, st[k])
    assert outs["on"] == outs["off"]
    assert all(s == "finished" for _, s in outs["on"].values())


def test_tier_off_constant_clock_stats_bit_identical(tiny_gpt):
    """The tier-off regression bar: two identical tier-off fleets
    under a constant clock produce the same outputs AND the same FULL
    stats() — the shared-tier code paths are provably dormant."""
    runs = []
    for _ in range(2):
        fleet = _fleet(tiny_gpt, n=2, clock=lambda: 0.0)
        res = _drive_pairs(fleet, _warm_trace(n=8, sampled=True))
        runs.append((_resdict(res),
                     json.loads(json.dumps(fleet.stats(),
                                           sort_keys=True,
                                           default=str))))
    assert runs[0] == runs[1]


# ---------------------------------------------------------------------------
# corrupt shared entries: discarded, recomputed, token-identical
# ---------------------------------------------------------------------------


def test_corrupt_shared_entry_discarded_and_recomputed(tiny_gpt):
    """Rot every resident shared entry mid-trace: later requests must
    fetch nothing poisoned — corrupt entries are discarded (counted,
    surfaced as shared_* corruption_detected events) and the requests
    finish token-identical to the tier-off recompute arm."""
    trace = lambda: _warm_trace(n=16, npref=3)
    base = _fleet(tiny_gpt, n=2, fleet_kw=dict(affinity_weight=0.0))
    expect = _resdict(_drive_pairs(base, trace()))

    obs = Observability(trace=False, metrics=False)
    fleet = _fleet(tiny_gpt, n=2, fleet_kw=dict(SHARED_FLEET_KW),
                   obs=obs)
    reqs = trace()
    got = dict(_resdict(_drive_pairs(fleet, reqs[:8])))
    store = fleet._shared
    assert len(store) > 0
    for h in list(store.hashes()):
        store._entries[h]["payload"]["k"].view(np.uint8)[0] ^= 0xFF
    got.update(_resdict(_drive_pairs(fleet, reqs[8:])))

    assert got == expect
    st = fleet.stats()
    assert st["num_shared_corrupt_discards"] >= 1, st
    assert st["num_lost_requests"] == 0
    sites = {e.get("site") for e in obs.recorder.tail()
             if e["kind"] == "corruption_detected"}
    assert any(str(s).startswith("shared_") for s in sites), sites
    store.check_integrity()


def test_shared_scrubber_coverage_counts(tiny_gpt):
    """The router-walked scrub: with ``shared_scrub_blocks`` > 0 the
    verified-entry counter grows tick over tick; with 0 the scrub is
    disabled and the counter stays flat."""
    for n, expect_scrub in ((8, True), (0, False)):
        fleet = _fleet(tiny_gpt, n=2, fleet_kw=dict(
            SHARED_FLEET_KW, shared_scrub_blocks=n))
        _drive_pairs(fleet, _warm_trace(n=8))
        st = fleet.stats()
        assert st["num_shared_publishes"] >= 1, st
        assert (st["num_shared_scrub_blocks_verified"] > 0) \
            is expect_scrub, st


# ---------------------------------------------------------------------------
# recorder + tenant attribution surfaces
# ---------------------------------------------------------------------------


def test_shared_events_recorded_and_tenant_rows_sum(tiny_gpt):
    obs = Observability(trace=False, metrics=False)
    fleet = _fleet(tiny_gpt, n=2, fleet_kw=dict(SHARED_FLEET_KW),
                   obs=obs)
    tenant = lambda k: "acme" if k % 2 == 0 else "bravo"
    res = _drive_pairs(fleet, _warm_trace(n=12, tenant=tenant))
    assert all(r.status == "finished" for r in res.values())
    kinds = {e["kind"] for e in obs.recorder.tail()}
    assert {"shared_publish", "shared_hit"} <= kinds, kinds
    st = fleet.stats()
    rows = st["tenants"]
    # the fractional ledger, shared-tier leg: per-tenant charges sum
    # to the __shared__ row, which is the tier's resident total
    assert rows["__shared__"]["shared_tier_bytes"] == pytest.approx(
        st["shared_tier_bytes"], abs=1e-3)
    charged = sum(r["shared_tier_bytes"] for t, r in rows.items()
                  if t != "__shared__")
    assert charged == pytest.approx(
        rows["__shared__"]["shared_tier_bytes"], abs=1e-3)
    assert any(rows.get(t, {}).get("shared_tier_bytes", 0) > 0
               for t in ("acme", "bravo")), rows


# ---------------------------------------------------------------------------
# process mode: publish/probe/fetch over the framed RPC wire
# ---------------------------------------------------------------------------


def test_process_mode_shared_tier_over_the_wire(tiny_gpt):
    """The shared tier rides the existing framed-RPC spill surface:
    a 2-process-replica fleet publishes, dedupes and seeds hits over
    the wire, token-identical to the in-process shared fleet — with a
    TORN response frame injected mid-trace (retried by the parent,
    zero lost, at-most-once preserved)."""
    inproc = _fleet(tiny_gpt, n=2, fleet_kw=dict(SHARED_FLEET_KW))
    expect = _resdict(_drive_pairs(inproc, _warm_trace(n=8)))
    ist = inproc.stats()
    assert ist["shared_tier_hits"] >= 1, ist

    faults = [FaultPlan([FaultSpec(site="wire", kind="transient",
                                   at=(7,))], seed=3), None]
    fleet = _fleet(tiny_gpt, n=2, process=True,
                   fleet_kw=dict(SHARED_FLEET_KW, rpc_retries=2),
                   faults=faults)
    try:
        got = _resdict(_drive_pairs(fleet, _warm_trace(n=8)))
        st = fleet.stats()
    finally:
        fleet.close()
    assert got == expect
    assert st["num_shared_publishes"] >= 1, st
    assert st["num_shared_dedupe"] >= 1, st
    assert st["shared_tier_hits"] >= 1, st
    assert st["num_rpc_retries"] >= 1, st
    assert st["num_lost_requests"] == 0


# ---------------------------------------------------------------------------
# composition: drain-and-migrate + SDC replay with the tier on
# ---------------------------------------------------------------------------


def test_drain_retire_and_sdc_compose_with_shared_tier(tiny_gpt):
    """The tier must not confuse the other fleet machinery: with SDC
    replay on, seeded shared hits replay clean (checks run, zero
    suspects — a hit really is recompute-identical); draining and
    retiring a replica mid-trace loses nothing, clears its published
    ledger, and the survivor keeps serving shared hits."""
    fleet = _fleet(tiny_gpt, n=2, fleet_kw=dict(
        SHARED_FLEET_KW, sdc_check_interval_ticks=2))
    reqs = _warm_trace(n=12)
    res = dict(_drive_pairs(fleet, reqs[:6]))
    # mid-trace clean shutdown of replica 0, work in flight
    for r in reqs[6:8]:
        fleet.add_request(r)
    fleet.step()
    fleet.drain_replica(0, dst=1, retire=True)
    assert fleet._published[0] == set()
    res.update(fleet.run(return_status=True))
    # chill the survivor's LOCAL tiers: flush its device blocks, let
    # the next tick publish them into the shared tier, then drop its
    # local spill copies — the shared tier is now the only warm copy,
    # so the final wave can only land warm through shared-tier seeding
    # (structural, not churn-dependent: hits below are guaranteed)
    survivor = fleet.replicas[1].engine
    survivor.allocator.flush_evictable()
    fleet.step()
    for h in list(survivor.spill.hashes()):
        survivor.spill._drop(h)
    for r in reqs[8:]:
        fleet.add_request(r)
    res.update(fleet.run(return_status=True))
    assert sorted(res) == sorted(r.uid for r in reqs)
    assert all(r.status == "finished" for r in res.values())
    st = fleet.stats()
    assert st["num_lost_requests"] == 0
    assert st["num_retired"] == 1 or st["replicas_alive"] == 1, st
    assert st["shared_tier_hits"] >= 1, st
    assert st["num_sdc_checks"] > 0, st
    assert st["num_sdc_suspects"] == 0, st
    fleet._shared.check_integrity()
    for _, rep in fleet._alive():
        rep.engine.check_allocator_integrity()


# ---------------------------------------------------------------------------
# the placement hot path: ONE chain-hash walk per decision
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("tier_on", [False, True])
def test_one_hash_walk_per_placement_decision(tiny_gpt, tier_on):
    """Regression pin for the hoist: ``add_request`` walks the
    prompt's chain hashes exactly once and hands them to ``_ranked``
    AND the shared-tier seeding — never a second walk, tier on or
    off, and a plain run adds none after placement."""
    fkw = dict(SHARED_FLEET_KW) if tier_on \
        else dict(affinity_weight=0.0)
    fleet = _fleet(tiny_gpt, n=2, fleet_kw=fkw)
    reqs = _warm_trace(n=6)
    for k, r in enumerate(reqs):
        fleet.add_request(r)
        assert fleet.stats()["num_hash_walks"] == k + 1
    fleet.run()
    assert fleet.stats()["num_hash_walks"] == len(reqs)
