"""Ulysses all-to-all sequence-parallel attention tests on the 8-device
mesh: head-resharded attention == full attention, forward and grads."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.ops.ulysses_attention import (
    ulysses_attention,
    ulysses_attention_reference,
)

CP = 8
B, H, D = 2, 8, 16  # H divisible by CP
S = 64


def _qkv(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (jax.random.normal(ks[0], (B, H, S, D)),
            jax.random.normal(ks[1], (B, H, S, D)),
            jax.random.normal(ks[2], (B, H, S, D)))


def _run(q, k, v, key_mask=None, causal=False):
    mesh = jax.make_mesh((CP,), ("context",))
    km = jnp.zeros((B, S), bool) if key_mask is None else key_mask

    def f(q, k, v, km):
        return ulysses_attention(q, k, v, km, causal, 0.25,
                                 axis_name="context")

    return jax.jit(jax.shard_map(
        f, mesh=mesh,
        in_specs=(P(None, None, "context"),) * 3 + (P(None, "context"),),
        out_specs=P(None, None, "context")))(q, k, v, km)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full_attention(causal):
    q, k, v = _qkv()
    out = _run(q, k, v, causal=causal)
    ref = ulysses_attention_reference(q, k, v, None, causal, 0.25)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ulysses_with_padding_mask():
    q, k, v = _qkv(1)
    km = jnp.asarray(np.random.RandomState(2).rand(B, S) < 0.25)
    out = _run(q, k, v, key_mask=km)
    ref = ulysses_attention_reference(q, k, v, km, False, 0.25)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ulysses_gradients_match_full():
    q, k, v = _qkv(3)
    mesh = jax.make_mesh((CP,), ("context",))
    km = jnp.zeros((B, S), bool)

    def loss(q, k, v, km):
        out = ulysses_attention(q, k, v, km, True, 0.25,
                                axis_name="context")
        return jax.lax.psum(jnp.sum(jnp.sin(out)), "context")

    g = jax.jit(jax.shard_map(
        jax.grad(loss, argnums=(0, 1, 2)), mesh=mesh,
        in_specs=(P(None, None, "context"),) * 3 + (P(None, "context"),),
        out_specs=(P(None, None, "context"),) * 3))(q, k, v, km)

    g_ref = jax.grad(lambda q, k, v: jnp.sum(jnp.sin(
        ulysses_attention_reference(q, k, v, None, True, 0.25))),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_ulysses_rejects_indivisible_heads():
    mesh = jax.make_mesh((CP,), ("context",))
    q = jnp.ones((1, 6, 8, 4))  # 6 heads, cp=8

    with pytest.raises(ValueError):
        jax.jit(jax.shard_map(
            lambda q: ulysses_attention(q, q, q, axis_name="context"),
            mesh=mesh, in_specs=P(None, None, "context"),
            out_specs=P(None, None, "context")))(q)


def test_ulysses_invariant_mask_under_vma_check():
    """A replicated / in-body default mask must work under the default
    vma checking (regression: all_gather of an invariant operand)."""
    q, k, v = _qkv(4)
    mesh = jax.make_mesh((CP,), ("context",))

    def f(q, k, v):
        km = jnp.zeros((B, q.shape[2]), bool)  # in-body, axis-invariant
        return ulysses_attention(q, k, v, km, False, 0.25,
                                 axis_name="context")

    out = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=(P(None, None, "context"),) * 3,
        out_specs=P(None, None, "context")))(q, k, v)
    ref = ulysses_attention_reference(q, k, v, None, False, 0.25)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ulysses_fused_attention_dropout():
    """Ulysses supports in-kernel attention-prob dropout (it is plain
    full-sequence flash per head subset — no blockwise merging):
    deterministic per seed, fresh masks per seed, kept entries match
    the dropout-free output scaled by 1/keep where kept."""
    q, k, v = _qkv()
    mesh = jax.make_mesh((CP,), ("context",))

    def f(seed):
        def g(q, k, v):
            return ulysses_attention(q, k, v, None, False, 0.25,
                                     axis_name="context",
                                     dropout_rate=0.15, dropout_seed=seed)
        return jax.jit(jax.shard_map(
            g, mesh=mesh, in_specs=(P(None, None, "context"),) * 3,
            out_specs=P(None, None, "context")))(q, k, v)

    o1, o2, o3 = f(5), f(5), f(6)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    assert (np.asarray(o1) != np.asarray(o3)).any()
    base = _run(q, k, v)
    # dropout output differs from the dropout-free one
    assert float(jnp.max(jnp.abs(o1 - base))) > 1e-3
    assert np.isfinite(np.asarray(o1)).all()


def test_ulysses_dropout_requires_seed():
    q, k, v = _qkv()
    mesh = jax.make_mesh((CP,), ("context",))
    with pytest.raises(ValueError, match="dropout_seed"):
        jax.jit(jax.shard_map(
            lambda q, k, v: ulysses_attention(
                q, k, v, None, False, 0.25, axis_name="context",
                dropout_rate=0.15),
            mesh=mesh, in_specs=(P(None, None, "context"),) * 3,
            out_specs=P(None, None, "context")))(q, k, v)
