"""DistributedDataParallel tests on the 8-device CPU mesh (upstream
analog: tests/distributed/DDP — shrunk world size, real collectives,
no mocks; SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel import DistributedDataParallel, flat_dist_call


def _mesh():
    return jax.make_mesh((8,), ("data",))


def _grads(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "a": jnp.asarray(rng.randn(4, 5).astype("float32")),
        "b": jnp.asarray(rng.randn(3).astype("float32")),
        "c": {"d": jnp.asarray(rng.randn(2, 2, 2).astype("float32"))},
    }


def _per_device_grads():
    """Stack 8 distinct grad pytrees along a leading device axis."""
    trees = [_grads(i) for i in range(8)]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


def _expected_mean():
    trees = [_grads(i) for i in range(8)]
    return jax.tree.map(lambda *xs: jnp.stack(xs).mean(0), *trees)


def _run_allreduce(ddp):
    mesh = _mesh()
    stacked = _per_device_grads()

    def f(g):
        g = jax.tree.map(lambda x: x[0], g)  # my shard
        return ddp.allreduce_grads(g)

    out = jax.jit(
        jax.shard_map(
            f, mesh=mesh,
            in_specs=P("data"),
            out_specs=P(),
        )
    )(stacked)
    return out


@pytest.mark.parametrize("delay", [False, True])
def test_allreduce_averages_across_devices(delay):
    ddp = DistributedDataParallel(axis_name="data", delay_allreduce=delay)
    out = _run_allreduce(ddp)
    exp = _expected_mean()
    for a, b in zip(jax.tree.leaves(out), jax.tree.leaves(exp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_small_message_size_many_buckets():
    """Tiny buckets (every leaf its own) must give identical results."""
    ddp = DistributedDataParallel(axis_name="data", message_size=1)
    out = _run_allreduce(ddp)
    exp = _expected_mean()
    for a, b in zip(jax.tree.leaves(out), jax.tree.leaves(exp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_no_average_sums():
    ddp = DistributedDataParallel(axis_name="data", gradient_average=False)
    out = _run_allreduce(ddp)
    trees = [_grads(i) for i in range(8)]
    exp = jax.tree.map(lambda *xs: jnp.stack(xs).sum(0), *trees)
    for a, b in zip(jax.tree.leaves(out), jax.tree.leaves(exp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)


def test_predivide_factor_preserves_mean():
    """Predivide changes intermediate scaling, not the final average."""
    ddp = DistributedDataParallel(axis_name="data", gradient_predivide_factor=8.0)
    out = _run_allreduce(ddp)
    exp = _expected_mean()
    for a, b in zip(jax.tree.leaves(out), jax.tree.leaves(exp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_allreduce_always_fp32_with_bf16_grads():
    ddp = DistributedDataParallel(axis_name="data", allreduce_always_fp32=True)
    mesh = _mesh()
    stacked = jax.tree.map(lambda x: x.astype(jnp.bfloat16), _per_device_grads())

    def f(g):
        g = jax.tree.map(lambda x: x[0], g)
        out = ddp.allreduce_grads(g)
        assert all(x.dtype == jnp.bfloat16 for x in jax.tree.leaves(out))
        return out

    out = jax.jit(
        jax.shard_map(
            f, mesh=mesh,
            in_specs=P("data"),
            out_specs=P(),
        )
    )(stacked)
    exp = _expected_mean()
    for a, b in zip(jax.tree.leaves(out), jax.tree.leaves(exp)):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b), rtol=0.05, atol=0.05
        )


def test_subgroup_allreduce():
    """process_group support via axis_index_groups: two groups of 4."""
    groups = ((0, 1, 2, 3), (4, 5, 6, 7))
    ddp = DistributedDataParallel(axis_name="data", axis_index_groups=groups)
    mesh = _mesh()
    stacked = _per_device_grads()

    def f(g):
        g = jax.tree.map(lambda x: x[0], g)
        return ddp.allreduce_grads(g)

    out = jax.jit(
        jax.shard_map(
            f, mesh=mesh,
            in_specs=P("data"),
            out_specs=P("data"),
        )
    )(stacked)
    # device 0 result = mean of devices 0-3; device 4 = mean of 4-7.
    # shard_map concatenates per-device outputs along the leading axis:
    # out["a"] is (8*4, 5); reshape to (8, 4, 5) to index devices.
    lo = jax.tree.map(lambda *xs: jnp.stack(xs).mean(0), *[_grads(i) for i in range(4)])
    hi = jax.tree.map(lambda *xs: jnp.stack(xs).mean(0), *[_grads(i) for i in range(4, 8)])
    a = np.asarray(out["a"]).reshape(8, 4, 5)
    np.testing.assert_allclose(a[0], np.asarray(lo["a"]), rtol=1e-5)
    np.testing.assert_allclose(a[4], np.asarray(hi["a"]), rtol=1e-5)


def test_ddp_end_to_end_training_step():
    """DP training: per-device batches, synced grads => identical params
    on every device (the upstream ddp_race_condition/amp_master_params
    consistency assertion)."""
    mesh = _mesh()
    rng = np.random.RandomState(0)
    X = jnp.asarray(rng.randn(8, 16, 10).astype("float32"))  # per-device batches
    Y = jnp.asarray(rng.randn(8, 16, 1).astype("float32"))
    params = {"w": jnp.asarray(rng.randn(10, 1).astype("float32"))}
    ddp = DistributedDataParallel(axis_name="data")

    from apex_tpu.optimizers import FusedSGD
    opt = FusedSGD(lr=0.05)
    ost = opt.init(params)

    def step(p, ost, x, y):
        def loss_fn(q):
            return jnp.mean((x @ q["w"] - y) ** 2)

        loss, grads = ddp.value_and_grad(loss_fn)(p)
        p2, ost2 = opt.step(grads, ost, p)
        return p2, ost2, jax.lax.pmean(loss, "data")

    sharded = jax.jit(
        jax.shard_map(
            step, mesh=mesh,
            in_specs=(P(), P(), P("data"), P("data")),
            out_specs=(P(), P(), P()),
        )
    )
    p, ost_out, loss0 = sharded(params, ost, X, Y)
    for _ in range(20):
        p, ost_out, loss = sharded(p, ost_out, X, Y)
    assert float(loss) < float(loss0)

    # replicated-output spec P() would fail to infer if devices disagreed;
    # double-check numerically vs single-device big-batch training
    big_p = params
    big_ost = opt.init(params)
    Xb, Yb = X.reshape(-1, 10), Y.reshape(-1, 1)
    for _ in range(21):
        g = jax.grad(lambda q: jnp.mean((Xb @ q["w"] - Yb) ** 2))(big_p)
        big_p, big_ost = opt.step(g, big_ost, big_p)
    np.testing.assert_allclose(
        np.asarray(p["w"]), np.asarray(big_p["w"]), rtol=1e-4, atol=1e-5
    )


def test_flat_dist_call():
    mesh = _mesh()
    xs = jnp.arange(8.0)

    def f(x):
        outs = flat_dist_call([x, x * 2], axis_name="data", op="sum")
        return outs[0], outs[1]

    a, b = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=P("data"), out_specs=(P(), P()))
    )(xs)
    assert float(a[0]) == 28.0  # sum 0..7
    assert float(b[0]) == 56.0


def test_mixed_vma_tree_not_double_reduced():
    """Review regression: an already-summed (unvarying) leaf bucketed with
    a varying leaf must not be psum'd again."""
    mesh = _mesh()
    for delay in (False, True):
        ddp = DistributedDataParallel(axis_name="data", delay_allreduce=delay)

        def f(x):
            unvarying = jnp.ones((3,))        # replicated, pre-summed
            tree = {"u": unvarying, "v": x}   # mixed with varying x
            return ddp.allreduce_grads(tree)

        out = jax.jit(
            jax.shard_map(f, mesh=mesh, in_specs=P("data"), out_specs=P())
        )(jnp.arange(8.0))
        # unvarying leaf: skip psum, divide by world -> 1/8
        np.testing.assert_allclose(np.asarray(out["u"]), 0.125, rtol=1e-6)
        # varying leaf: psum/world = mean = 3.5
        np.testing.assert_allclose(np.asarray(out["v"]), 3.5, rtol=1e-6)


def test_bootstrap_single_process_noop_and_env_parsing(monkeypatch):
    """init_process_group (the torch.distributed.init_process_group
    analog): single-process call no-ops, partial env raises, and the
    world helpers report CHIP world (torch semantics), not host count."""
    import pytest

    from apex_tpu.parallel import (
        get_rank,
        get_world_size,
        init_process_group,
    )
    from apex_tpu.parallel import bootstrap

    for var in ("MASTER_ADDR", "MASTER_PORT", "JAX_NUM_PROCESSES",
                "JAX_PROCESS_ID"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setattr(bootstrap, "_mode", "")
    init_process_group()  # no coordinator, no auto: must no-op
    assert bootstrap._mode == "noop"
    # torch world size is per-rank(-GPU): the chip count, not the host
    # count — on the 8-device sim that is 8
    assert get_world_size() == jax.device_count() == 8
    assert get_rank() == 0
    init_process_group()  # idempotent

    # partial env (stale MASTER_ADDR, no JAX_NUM_PROCESSES/JAX_PROCESS_ID)
    # must raise clearly, not crash inside jax.distributed.initialize —
    # and a no-op latch must NOT swallow a later call that wants a
    # cluster (the silent-solo-training failure mode)
    monkeypatch.setenv("MASTER_ADDR", "10.0.0.1")
    with pytest.raises(ValueError, match="must all be provided"):
        init_process_group()
    # torchrun-style WORLD_SIZE/RANK are per-GPU: ignored, still raises
    monkeypatch.setenv("WORLD_SIZE", "32")
    monkeypatch.setenv("RANK", "0")
    with pytest.raises(ValueError, match="not consumed"):
        init_process_group()
