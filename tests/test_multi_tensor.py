"""multi_tensor op tests (upstream analog: tests/L0/run_optimizers +
the amp unscale path, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.multi_tensor_apply import MultiTensorApply, multi_tensor_applier
from apex_tpu.ops import multi_tensor as mt


def _lists(seed=0, n=5):
    rng = np.random.RandomState(seed)
    shapes = [(3, 4), (16,), (2, 2, 2), (1,), (8, 3)][:n]
    return [jnp.asarray(rng.randn(*s).astype("float32")) for s in shapes]


def test_applier_signature_parity():
    assert multi_tensor_applier.chunk_size == 2048 * 32
    assert MultiTensorApply.available


def test_scale():
    xs = _lists()
    outs, flag = multi_tensor_applier(mt.multi_tensor_scale, None, [xs, xs], 0.5)
    assert not bool(flag)
    for x, o in zip(xs, outs):
        np.testing.assert_allclose(np.asarray(o), np.asarray(x) * 0.5, rtol=1e-6)


def test_scale_detects_inf():
    xs = _lists()
    xs[2] = xs[2].at[0, 0, 0].set(jnp.inf)
    _, flag = multi_tensor_applier(mt.multi_tensor_scale, None, [xs, xs], 1.0)
    assert bool(flag)


def test_scale_respects_incoming_noop_flag():
    xs = _lists()
    outs, flag = multi_tensor_applier(
        mt.multi_tensor_scale, jnp.asarray(True), [xs, xs], 0.5
    )
    assert bool(flag)
    for x, o in zip(xs, outs):  # early-exit semantics: untouched
        np.testing.assert_allclose(np.asarray(o), np.asarray(x))


def test_axpby():
    xs, ys = _lists(0), _lists(1)
    outs, flag = multi_tensor_applier(
        mt.multi_tensor_axpby, None, [xs, ys, xs], 2.0, -1.0
    )
    assert not bool(flag)
    for x, y, o in zip(xs, ys, outs):
        np.testing.assert_allclose(np.asarray(o), 2 * np.asarray(x) - np.asarray(y), rtol=1e-6)


def test_axpby_respects_incoming_noop_flag():
    xs, ys = _lists(0), _lists(1)
    outs, flag = multi_tensor_applier(
        mt.multi_tensor_axpby, jnp.asarray(True), [xs, ys, ys], 2.0, -1.0
    )
    assert bool(flag)
    for y, o in zip(ys, outs):  # early-exit: last list (outputs) untouched
        np.testing.assert_allclose(np.asarray(o), np.asarray(y))


def test_l2norm_global_and_per_tensor():
    xs = _lists()
    g, per = multi_tensor_applier(mt.multi_tensor_l2norm, None, [xs], True)
    ref_per = np.array([np.linalg.norm(np.asarray(x)) for x in xs])
    np.testing.assert_allclose(np.asarray(per), ref_per, rtol=1e-5)
    np.testing.assert_allclose(float(g), np.sqrt((ref_per ** 2).sum()), rtol=1e-5)


def test_adam_matches_reference_loop():
    """Fused flat-buffer Adam == per-tensor eager reference (the upstream
    test_fused_optimizer.py pattern)."""
    rng = np.random.RandomState(3)
    ps = [jnp.asarray(rng.randn(4, 4).astype("float32")),
          jnp.asarray(rng.randn(7).astype("float32"))]
    gs = [jnp.asarray(rng.randn(4, 4).astype("float32")),
          jnp.asarray(rng.randn(7).astype("float32"))]
    ms = [jnp.zeros_like(p) for p in ps]
    vs = [jnp.zeros_like(p) for p in ps]
    lr, b1, b2, eps, wd = 1e-2, 0.9, 0.999, 1e-8, 0.01

    for step in (1, 2, 3):
        out = multi_tensor_applier(
            mt.multi_tensor_adam, None, [gs, ps, ms, vs],
            lr, b1, b2, eps, step, mt.ADAM_MODE_ADAMW, True, wd,
        )
        ps, ms, vs = out

    # eager reference
    rp = [np.asarray(x) for x in
          [jnp.asarray(rng.randn(0))] ]  # placeholder, rebuilt below
    rng = np.random.RandomState(3)
    rp = [rng.randn(4, 4).astype("float32"), rng.randn(7).astype("float32")]
    rg = [rng.randn(4, 4).astype("float32"), rng.randn(7).astype("float32")]
    rm = [np.zeros_like(p) for p in rp]
    rv = [np.zeros_like(p) for p in rp]
    for step in (1, 2, 3):
        for i in range(2):
            bc1 = 1 - b1 ** step
            bc2 = 1 - b2 ** step
            rm[i] = b1 * rm[i] + (1 - b1) * rg[i]
            rv[i] = b2 * rv[i] + (1 - b2) * rg[i] ** 2
            upd = (rm[i] / bc1) / (np.sqrt(rv[i] / bc2) + eps) + wd * rp[i]
            rp[i] = rp[i] - lr * upd
    for a, b in zip(ps, rp):
        np.testing.assert_allclose(np.asarray(a), b, rtol=1e-5, atol=1e-6)


def test_sgd_momentum_first_run():
    ps = _lists(0, 2)
    gs = _lists(1, 2)
    moms = [jnp.zeros_like(p) for p in ps]
    out = multi_tensor_applier(
        mt.multi_tensor_sgd, None, [gs, ps, moms],
        0.0, 0.9, 0.0, 0.1, False, True, False,
    )
    new_p, new_mom = out
    for g, m in zip(gs, new_mom):
        np.testing.assert_allclose(np.asarray(m), np.asarray(g), rtol=1e-6)
    for p, g, np_ in zip(ps, gs, new_p):
        np.testing.assert_allclose(np.asarray(np_), np.asarray(p) - 0.1 * np.asarray(g), rtol=1e-5)


def test_mixed_dtype_lists():
    """bf16 params with fp32 masters: fused op keeps master precision."""
    ps = [jnp.ones((4,), jnp.bfloat16)]
    master = [jnp.ones((4,), jnp.float32)]
    gs = [jnp.full((4,), 0.001, jnp.bfloat16)]
    ms = [jnp.zeros((4,), jnp.float32)]
    vs = [jnp.zeros((4,), jnp.float32)]
    out = multi_tensor_applier(
        mt.multi_tensor_adam, None, [gs, ps, ms, vs, master],
        1e-3, 0.9, 0.999, 1e-8, 1, mt.ADAM_MODE_ADAMW, True, 0.0,
    )
    new_p, _, _, new_master = out
    assert new_p[0].dtype == jnp.bfloat16
    assert new_master[0].dtype == jnp.float32
    # master moved even though the bf16 cast may round
    assert float(new_master[0][0]) != 1.0


def test_jit_single_fusion():
    """The whole multi-tensor op must be jittable as one computation."""
    xs = _lists()

    @jax.jit
    def f(xs):
        outs, flag = mt.multi_tensor_scale(2048 * 32, None, [xs, xs], 2.0)
        return outs, flag

    outs, flag = f(xs)
    assert not bool(flag)
    np.testing.assert_allclose(np.asarray(outs[0]), np.asarray(xs[0]) * 2, rtol=1e-6)


def test_l2norm_scale_fused():
    """multi_tensor_l2norm_scale: out = in*scale with norms of the scaled
    values from the same pass (csrc/multi_tensor_l2norm_scale_kernel.cu)."""
    xs = [jnp.asarray([3.0, 4.0]), jnp.asarray([12.0])]
    outs, gnorm, per, flag = multi_tensor_applier(
        mt.multi_tensor_l2norm_scale, None,
        [xs, [jnp.zeros_like(x) for x in xs]], 0.5, per_tensor=True)
    assert jnp.allclose(outs[0], jnp.asarray([1.5, 2.0]))
    assert jnp.allclose(per, jnp.asarray([2.5, 6.0]))
    assert jnp.allclose(gnorm, 6.5)  # sqrt(2.5^2 + 6^2)
    assert not bool(flag)

    # inf detection + incoming noop flag passthrough
    bad = [jnp.asarray([jnp.inf])]
    _, _, _, flag2 = multi_tensor_applier(
        mt.multi_tensor_l2norm_scale, None, [bad, bad], 1.0)
    assert bool(flag2)
