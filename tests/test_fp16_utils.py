"""fp16_utils tests (upstream analog: tests/distributed/amp_master_params
master↔model consistency + the legacy FP16_Optimizer smoke paths,
SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.fp16_utils import (
    FP16_Optimizer,
    master_params_to_model_params,
    model_grads_to_master_grads,
    network_to_half,
    prep_param_lists,
)
from apex_tpu.optimizers import FusedSGD


def _params():
    rng = np.random.RandomState(0)
    return {
        "w": jnp.asarray(rng.randn(4, 3).astype("float32")),
        "b": jnp.asarray(rng.randn(3).astype("float32")),
        "step": jnp.asarray(3, jnp.int32),  # non-float leaves pass through
    }


def test_network_to_half_and_back():
    p = _params()
    h = network_to_half(p)
    assert h["w"].dtype == jnp.bfloat16
    assert h["step"].dtype == jnp.int32  # untouched
    h16 = network_to_half(p, jnp.float16)
    assert h16["w"].dtype == jnp.float16


def test_prep_param_lists_roundtrip():
    p = network_to_half(_params())
    model, master = prep_param_lists(p)
    assert master["w"].dtype == jnp.float32
    back = master_params_to_model_params(model, master)
    for a, b in zip(jax.tree.leaves(back), jax.tree.leaves(model)):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(np.asarray(a, np.float32),
                                      np.asarray(b, np.float32))


def test_flat_master_roundtrip():
    p = network_to_half({"w": jnp.ones((2, 3)), "b": jnp.zeros((5,))})
    model, flat = prep_param_lists(p, flat_master=True)
    assert flat.shape == (11,) and flat.dtype == jnp.float32
    back = master_params_to_model_params(model, flat, flat_master=True)
    for a, b in zip(jax.tree.leaves(back), jax.tree.leaves(model)):
        np.testing.assert_array_equal(np.asarray(a, np.float32),
                                      np.asarray(b, np.float32))
    g = model_grads_to_master_grads(model, flat_master=True)
    assert g.shape == (11,) and g.dtype == jnp.float32


def test_fp16_optimizer_master_model_consistency():
    """The reference's amp_master_params check: after steps, model params
    equal masters cast to model dtype."""
    params = network_to_half({"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))})
    opt = FP16_Optimizer(FusedSGD(lr=0.1), static_loss_scale=128.0)
    state = opt.init(params)

    grads = jax.tree.map(lambda p: jnp.ones_like(p) * 128.0, params)  # scaled
    p = params
    for _ in range(3):
        p, state, skipped = opt.step(grads, state, p)
        assert not bool(skipped)
    masters = state.inner.master
    cast = jax.tree.map(lambda mp, m: m.astype(mp.dtype), p, masters)
    for a, b in zip(jax.tree.leaves(p), jax.tree.leaves(cast)):
        np.testing.assert_array_equal(np.asarray(a, np.float32),
                                      np.asarray(b, np.float32))
    # 3 steps of lr 0.1 on unit (unscaled) grads from 1.0 → 0.7
    np.testing.assert_allclose(np.asarray(p["w"], np.float32), 0.7,
                               rtol=1e-2)


def test_fp16_optimizer_dynamic_scale_backoff():
    params = network_to_half({"w": jnp.ones((2, 2))})
    opt = FP16_Optimizer(FusedSGD(lr=0.1), dynamic_loss_scale=True)
    state = opt.init(params)
    assert float(opt.loss_scale(state)) == 2.0 ** 16

    bad = {"w": jnp.full((2, 2), jnp.inf, jnp.bfloat16)}
    p, state, skipped = opt.step(bad, state, params)
    assert bool(skipped)
    assert float(opt.loss_scale(state)) == 2.0 ** 15
    np.testing.assert_array_equal(np.asarray(p["w"], np.float32),
                                  np.asarray(params["w"], np.float32))


def test_fp16_optimizer_state_dict_roundtrip():
    params = network_to_half({"w": jnp.ones((2, 2))})
    opt = FP16_Optimizer(FusedSGD(lr=0.1), dynamic_loss_scale=True)
    state = opt.init(params)
    bad = {"w": jnp.full((2, 2), jnp.inf, jnp.bfloat16)}
    _, state, _ = opt.step(bad, state, params)

    sd = opt.state_dict(state)
    restored = opt.load_state_dict(jax.tree.map(np.asarray, sd))
    assert float(restored.scaler.loss_scale) == float(state.scaler.loss_scale)
    assert int(restored.scaler.steps_skipped) == 1


def test_fp16_optimizer_jit_scaled_loss_loop():
    """End-to-end: scaled loss -> grads -> step inside jit; loss falls."""
    params = network_to_half({"w": jnp.asarray(
        np.random.RandomState(0).randn(8, 1).astype("float32") * 0.5)})
    X = jnp.asarray(np.random.RandomState(1).randn(32, 8).astype("float32"))
    y = X @ np.random.RandomState(2).randn(8, 1).astype("float32")
    opt = FP16_Optimizer(FusedSGD(lr=0.05), dynamic_loss_scale=True)
    state = opt.init(params)

    def loss_fn(p):
        pred = X.astype(jnp.bfloat16) @ p["w"]
        return jnp.mean((pred.astype(jnp.float32) - y) ** 2)

    @jax.jit
    def train_step(p, state):
        # legacy flow: backward() on the SCALED loss; step() unscales
        def scaled(p):
            return opt.scale_loss(loss_fn(p), state)

        loss_scaled, grads = jax.value_and_grad(scaled)(p)
        p2, state2, _ = opt.step(grads, state, p)
        return p2, state2, loss_scaled / state.scaler.loss_scale

    losses = []
    p = params
    for _ in range(25):
        p, state, l = train_step(p, state)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.5
