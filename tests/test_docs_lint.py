"""Doc-drift lint as a tier-1 test: every ``EngineConfig`` /
``TenantQuota`` field and every top-level ``stats()`` key must be
named in docs/serving.md or docs/robustness.md — the next knob or
counter cannot land undocumented (tools/check_docs.py)."""

import importlib.util
from pathlib import Path


def _load_check_docs():
    path = Path(__file__).resolve().parents[1] / "tools" / "check_docs.py"
    spec = importlib.util.spec_from_file_location("_check_docs", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_serving_surface_is_documented():
    mod = _load_check_docs()
    missing = mod.main()
    assert missing == [], (
        "undocumented serving surface (add the literal name to "
        "docs/serving.md or docs/robustness.md): " + repr(missing))


def test_lint_actually_detects_drift(monkeypatch, tmp_path):
    """The lint must FAIL on a genuinely missing name — guard against
    the checker rotting into a tautology."""
    mod = _load_check_docs()
    orig = mod.collect_names

    def with_phantom():
        return orig() + [("stats() key", "phantom_counter_xyz")]

    monkeypatch.setattr(mod, "collect_names", with_phantom)
    missing = mod.main()
    assert ("stats() key", "phantom_counter_xyz") in missing
