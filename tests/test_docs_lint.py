"""Doc-drift lint as a tier-1 test: every ``EngineConfig`` /
``TenantQuota`` field and every top-level ``stats()`` key must be
named in docs/serving.md or docs/robustness.md, and every trace event
type, flight-recorder event kind, and exported metric name must be
named in docs/observability.md — the next knob, counter, event, or
metric cannot land undocumented (tools/check_docs.py). Each surface
has a phantom-name self-test so the checker cannot rot into a
tautology."""

import importlib.util
from pathlib import Path


def _load_check_docs():
    path = Path(__file__).resolve().parents[1] / "tools" / "check_docs.py"
    spec = importlib.util.spec_from_file_location("_check_docs", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_serving_surface_is_documented():
    mod = _load_check_docs()
    missing = mod.main()
    assert missing == [], (
        "undocumented serving surface (add the literal name to "
        "docs/serving.md or docs/robustness.md): " + repr(missing))


def test_lint_actually_detects_drift(monkeypatch, tmp_path):
    """The lint must FAIL on a genuinely missing name — guard against
    the checker rotting into a tautology."""
    mod = _load_check_docs()
    orig = mod.collect_names

    def with_phantom():
        return orig() + [("stats() key", "phantom_counter_xyz")]

    monkeypatch.setattr(mod, "collect_names", with_phantom)
    missing = mod.main()
    assert ("stats() key", "phantom_counter_xyz") in missing


def test_lint_detects_phantom_observability_names(monkeypatch):
    """The observability surfaces are checked against
    docs/observability.md specifically: a phantom metric, trace event
    type, or recorder kind must each be flagged."""
    mod = _load_check_docs()
    orig = mod.collect_names
    phantoms = [("metric", "serving_phantom_metric_s"),
                ("trace event type", "phantom_event"),
                ("recorder event kind", "phantom_kind")]

    def with_phantoms():
        return orig() + phantoms

    monkeypatch.setattr(mod, "collect_names", with_phantoms)
    missing = mod.main()
    for p in phantoms:
        assert p in missing


def test_lint_detects_phantom_fleet_names(monkeypatch):
    """The fleet surface is checked against docs/fleet.md
    specifically: a phantom FleetConfig knob or fleet stats() key must
    be flagged."""
    mod = _load_check_docs()
    orig = mod.collect_names
    phantoms = [("FleetConfig field", "phantom_fleet_knob"),
                ("fleet stats() key", "num_phantom_fleet_counter")]

    def with_phantoms():
        return orig() + phantoms

    monkeypatch.setattr(mod, "collect_names", with_phantoms)
    missing = mod.main()
    for p in phantoms:
        assert p in missing


def test_lint_detects_phantom_integrity_names(monkeypatch):
    """The integrity surface is checked against docs/robustness.md
    specifically: a phantom integrity knob/counter must be flagged."""
    mod = _load_check_docs()
    orig = mod.collect_names
    phantom = ("integrity surface", "num_phantom_integrity_counter")

    def with_phantom():
        return orig() + [phantom]

    monkeypatch.setattr(mod, "collect_names", with_phantom)
    missing = mod.main()
    assert phantom in missing


def test_lint_detects_phantom_mesh_names(monkeypatch):
    """The mesh surface is checked against docs/serving.md
    specifically: a phantom mesh knob/stat must be flagged."""
    mod = _load_check_docs()
    orig = mod.collect_names
    phantom = ("mesh surface", "phantom_mesh_axis_stat")

    def with_phantom():
        return orig() + [phantom]

    monkeypatch.setattr(mod, "collect_names", with_phantom)
    missing = mod.main()
    assert phantom in missing


def test_mesh_names_are_live_surfaces():
    """MESH_NAMES cross-checks itself against the live config and
    stats surfaces: naming a nonexistent knob/key raises, so a rename
    cannot silently unpin the serving.md routing."""
    mod = _load_check_docs()
    names = mod.collect_names()
    mesh = {n for k, n in names if k == "mesh surface"}
    assert mesh == set(mod.MESH_NAMES)
    live = {n for k, n in names if k != "mesh surface"}
    assert mesh <= live


def test_mesh_names_are_checked_against_serving_doc():
    """The mesh kinds map to docs/serving.md alone — every MESH_NAMES
    entry must appear there (the "Mesh sharding" section)."""
    mod = _load_check_docs()
    mesh_text = mod._docs_text(mod.MESH_DOCS)
    for name in mod.MESH_NAMES:
        assert name in mesh_text, name


def test_integrity_names_are_live_surfaces():
    """INTEGRITY_NAMES cross-checks itself against the live config and
    stats surfaces: naming a nonexistent knob/key raises, so a rename
    cannot silently unpin the robustness.md routing."""
    mod = _load_check_docs()
    names = mod.collect_names()
    integ = {n for k, n in names if k == "integrity surface"}
    assert integ == set(mod.INTEGRITY_NAMES)
    live = {n for k, n in names if k != "integrity surface"}
    assert integ <= live


def test_integrity_names_are_checked_against_robustness_doc():
    """The integrity kinds map to docs/robustness.md alone — a name
    present only in fleet.md must not satisfy them (the fleet knob
    sdc_check_interval_ticks is deliberately documented in BOTH)."""
    mod = _load_check_docs()
    rob_text = mod._docs_text(mod.ROBUSTNESS_DOCS)
    for name in mod.INTEGRITY_NAMES:
        assert name in rob_text, name


def test_fleet_names_are_checked_against_their_doc():
    """A name present only in docs/fleet.md must NOT satisfy a
    serving-kind check and vice versa — the fleet kinds map to their
    own doc file."""
    mod = _load_check_docs()
    fleet_text = mod._docs_text(mod.FLEET_DOCS)
    serving_text = mod._docs_text(mod.SERVING_DOCS)
    # a fleet-only knob name lives in fleet.md, not serving.md
    assert "migrate_spill_payloads" in fleet_text
    assert "migrate_spill_payloads" not in serving_text


def test_observability_names_are_checked_against_their_doc():
    """A name present only in serving.md must NOT satisfy an
    observability-kind check (and vice versa the real names pass):
    the kinds map to their own doc files."""
    mod = _load_check_docs()
    # "spec_tokens" appears in serving.md but not observability.md —
    # as a metric name it must read as missing
    serving_text = mod._docs_text(mod.SERVING_DOCS)
    obs_text = mod._docs_text(mod.OBS_DOCS)
    assert "spec_tokens" in serving_text
    assert "spec_tokens" not in obs_text


def test_lint_detects_phantom_train_sharded_names(monkeypatch):
    """The sharded-train surface is checked against docs/training.md
    specifically: a phantom GSPMD knob/stat must be flagged."""
    mod = _load_check_docs()
    orig = mod.collect_names
    phantom = ("train sharded surface", "phantom_zero_shard_stat")

    def with_phantom():
        return orig() + [phantom]

    monkeypatch.setattr(mod, "collect_names", with_phantom)
    missing = mod.main()
    assert phantom in missing


def test_train_sharded_names_are_live_surfaces():
    """TRAIN_SHARDED_NAMES cross-checks itself against the live
    build_train_step signature / optimizer stats / TrainStep surfaces:
    naming a nonexistent knob raises, so a rename cannot silently
    unpin the docs/training.md routing."""
    mod = _load_check_docs()
    names = mod.collect_names()
    train = {n for k, n in names if k == "train sharded surface"}
    assert train == set(mod.TRAIN_SHARDED_NAMES)


def test_train_sharded_names_are_checked_against_training_doc():
    """The sharded-train kinds map to docs/training.md alone — every
    TRAIN_SHARDED_NAMES entry must appear there (the "Sharded
    training" section)."""
    mod = _load_check_docs()
    train_text = mod._docs_text(mod.TRAIN_DOCS)
    for name in mod.TRAIN_SHARDED_NAMES:
        assert name in train_text, name
