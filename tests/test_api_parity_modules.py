"""Parity tests for the small API-parity modules: apex.mlp,
apex.fused_dense, contrib xentropy, contrib clip_grad (upstream analogs:
tests/L0/run_mlp, tests/L0/run_fused_dense, contrib/test/xentropy —
fused-vs-composed numerical equivalence, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.clip_grad import clip_grad_norm_
from apex_tpu.contrib.xentropy import (
    SoftmaxCrossEntropyLoss,
    softmax_cross_entropy_loss,
)
from apex_tpu.fused_dense import DenseNoBias, FusedDense, FusedDenseGeluDense
from apex_tpu.mlp import MLP


# ---------------------------------------------------------------- mlp

def test_mlp_matches_composed():
    sizes = (16, 32, 24, 8)
    model = MLP(sizes, bias=True, activation="relu")
    x = jnp.asarray(np.random.RandomState(0).randn(4, 16).astype("float32"))
    params = model.init(jax.random.PRNGKey(0), x)
    y = model.apply(params, x)

    h = x
    layers = params["params"]
    for i in range(3):
        w = layers[f"layer_{i}"]["kernel"]
        b = layers[f"layer_{i}"]["bias"]
        h = h @ w + b
        if i < 2:
            h = jax.nn.relu(h)
    np.testing.assert_allclose(np.asarray(y), np.asarray(h), rtol=1e-6)


def test_mlp_grads_flow_and_no_bias():
    model = MLP((8, 8, 4), bias=False, activation="sigmoid")
    x = jnp.ones((2, 8))
    params = model.init(jax.random.PRNGKey(1), x)
    g = jax.grad(lambda p: jnp.sum(model.apply(p, x)))(params)
    assert all(bool(jnp.any(l != 0)) for l in jax.tree.leaves(g))
    assert "bias" not in params["params"]["layer_0"]


def test_mlp_validation():
    with pytest.raises(ValueError):
        MLP((16,)).init(jax.random.PRNGKey(0), jnp.ones((1, 16)))
    with pytest.raises(ValueError):
        MLP((16, 8), activation="tanh").init(
            jax.random.PRNGKey(0), jnp.ones((1, 16)))


# -------------------------------------------------------- fused_dense

def test_fused_dense_matches_composed():
    layer = FusedDense(12, 20)
    x = jnp.asarray(np.random.RandomState(0).randn(5, 12).astype("float32"))
    params = layer.init(jax.random.PRNGKey(0), x)
    y = layer.apply(params, x)
    ref = x @ params["params"]["kernel"] + params["params"]["bias"]
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-6)


def test_dense_no_bias():
    layer = DenseNoBias(6, 3)
    x = jnp.ones((2, 6))
    params = layer.init(jax.random.PRNGKey(0), x)
    assert set(params["params"].keys()) == {"kernel"}
    y = layer.apply(params, x)
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(x @ params["params"]["kernel"]),
                               rtol=1e-6)


def test_fused_dense_gelu_dense_matches_composed():
    layer = FusedDenseGeluDense(8, 32, 8)
    x = jnp.asarray(np.random.RandomState(1).randn(3, 8).astype("float32"))
    params = layer.init(jax.random.PRNGKey(0), x)
    y = layer.apply(params, x)
    p = params["params"]
    h = x @ p["dense1"]["kernel"] + p["dense1"]["bias"]
    h = jax.nn.gelu(h)
    ref = h @ p["dense2"]["kernel"] + p["dense2"]["bias"]
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5,
                               atol=1e-6)


def test_fused_dense_bf16_io():
    layer = FusedDense(8, 8)
    x = jnp.ones((2, 8), jnp.bfloat16)
    params = layer.init(jax.random.PRNGKey(0), x)
    assert layer.apply(params, x).dtype == jnp.bfloat16


# ------------------------------------------------------------ xentropy

def test_xentropy_matches_log_softmax():
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(6, 50).astype("float32"))
    labels = jnp.asarray(rng.randint(1, 50, 6))
    loss = softmax_cross_entropy_loss(logits, labels)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ref = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref), rtol=1e-5)


def test_xentropy_label_smoothing():
    rng = np.random.RandomState(1)
    logits = jnp.asarray(rng.randn(4, 20).astype("float32"))
    labels = jnp.asarray(rng.randint(1, 20, 4))
    eps = 0.1
    loss = SoftmaxCrossEntropyLoss.apply(logits, labels, smoothing=eps)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    # smoothed target: (1-eps) one-hot + eps/V uniform
    smooth = -jnp.mean(logp, axis=-1)
    ref = (1 - eps) * nll + eps * smooth
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref), rtol=1e-5)


def test_xentropy_padding_idx_and_grad():
    logits = jnp.asarray(np.random.RandomState(2).randn(4, 10)
                         .astype("float32"))
    labels = jnp.asarray([3, 0, 5, 0])  # padding_idx=0 rows → zero loss
    loss = softmax_cross_entropy_loss(logits, labels, padding_idx=0)
    assert float(loss[1]) == 0.0 and float(loss[3]) == 0.0
    assert float(loss[0]) > 0.0

    g = jax.grad(lambda l: jnp.sum(
        softmax_cross_entropy_loss(l, labels, padding_idx=0)))(logits)
    # padded rows contribute no gradient
    np.testing.assert_allclose(np.asarray(g[1]), 0.0, atol=1e-7)
    # live rows: softmax - one_hot
    probs = jax.nn.softmax(logits[0])
    expect = probs - jax.nn.one_hot(3, 10)
    np.testing.assert_allclose(np.asarray(g[0]), np.asarray(expect),
                               rtol=1e-5, atol=1e-6)


def test_xentropy_half_to_float():
    logits = jnp.ones((2, 8), jnp.bfloat16)
    labels = jnp.asarray([1, 2])
    assert softmax_cross_entropy_loss(
        logits, labels, half_to_float=True).dtype == jnp.float32
    assert softmax_cross_entropy_loss(
        logits, labels).dtype == jnp.bfloat16


# ----------------------------------------------------------- clip_grad

def _grad_tree(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "a": jnp.asarray(rng.randn(5, 3).astype("float32")),
        "b": {"c": jnp.asarray(rng.randn(7).astype("float32"))},
    }


def test_clip_grad_norm_clips():
    grads = _grad_tree()
    flat = np.concatenate([np.asarray(l).ravel()
                           for l in jax.tree.leaves(grads)])
    true_norm = float(np.linalg.norm(flat))
    max_norm = true_norm / 2

    clipped, total = clip_grad_norm_(grads, max_norm)
    np.testing.assert_allclose(float(total), true_norm, rtol=1e-5)
    new_flat = np.concatenate([np.asarray(l).ravel()
                               for l in jax.tree.leaves(clipped)])
    np.testing.assert_allclose(np.linalg.norm(new_flat), max_norm,
                               rtol=1e-4)


def test_clip_grad_norm_noop_below_threshold():
    grads = _grad_tree()
    clipped, total = clip_grad_norm_(grads, 1e9)
    for a, b in zip(jax.tree.leaves(clipped), jax.tree.leaves(grads)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_clip_grad_norm_inf_norm():
    grads = _grad_tree()
    flat = np.concatenate([np.asarray(l).ravel()
                           for l in jax.tree.leaves(grads)])
    _, total = clip_grad_norm_(grads, 1.0, norm_type=float("inf"))
    np.testing.assert_allclose(float(total), np.abs(flat).max(), rtol=1e-6)


def test_clip_grad_norm_jit_composes():
    grads = _grad_tree()

    @jax.jit
    def f(g):
        return clip_grad_norm_(g, 1.0)

    clipped, total = f(grads)
    assert np.isfinite(float(total))
