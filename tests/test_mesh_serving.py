"""Mesh-sharded serving (tier-1, CPU, 8 virtual devices): the GSPMD
``("batch", "model")`` mesh promotion of the inference engine
(docs/serving.md "Mesh sharding").

The certification matrix ISSUE 15 names: mesh (1, 1) bit-identical to
the pre-mesh engine (outputs, statuses, the FULL stats() dict —
greedy+sampled x spec on/off x int8 quantization), token-identity of
request outputs across mesh shapes, compile counts still pinned at one
per program under the mesh, the hlo_audit collective contract (zero
collectives at a 1-sized model axis, all-reduce traffic once heads
split), snapshot/restore + 1-replica-fleet identity with mesh-sharded
engines, allocator integrity after mesh-sharded LRU churn — plus the
old tp=2 shard_map decode smoke folded into a regular mesh test."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.models import GPTConfig, GPTLMHeadModel
from apex_tpu.serving import (
    EngineConfig,
    FleetConfig,
    FleetRouter,
    InferenceEngine,
    Request,
    SamplingParams,
    build_mesh,
    expected_collectives,
    validate_mesh_shape,
)
from apex_tpu.serving import mesh as mesh_lib
from apex_tpu.utils.hlo_audit import (
    assert_collective_contract,
    collective_stats,
)

CONST_CLOCK = lambda: 0.0  # noqa: E731 — constant-clock stats compare


@pytest.fixture(scope="module")
def tiny():
    cfg = GPTConfig.tiny(dropout=0.0, remat=False)
    model = GPTLMHeadModel(cfg)
    params = model.init(
        jax.random.PRNGKey(0),
        jnp.asarray(np.random.RandomState(0).randint(
            0, cfg.vocab_size, (1, 8))))
    return cfg, model, params


def _config(mesh_shape=(1, 1), **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 32)
    kw.setdefault("max_prefill_len", 8)
    kw.setdefault("max_seq_len", 32)
    kw.setdefault("decode_steps", 2)
    kw.setdefault("seed", 7)
    return EngineConfig(mesh_shape=mesh_shape, **kw)


def _mixed_requests(cfg, n=5, sampled=True):
    """A seeded mixed workload: varied prompt lengths, greedy AND
    sampled lanes (per-request keys make the draws mesh-invariant)."""
    rr = np.random.RandomState(3)
    out = []
    for i in range(n):
        sp = (SamplingParams(temperature=0.7, top_k=8, top_p=0.9)
              if sampled and i % 2 else SamplingParams())
        out.append(Request(
            uid=f"r{i}", prompt=list(rr.randint(0, cfg.vocab_size, 7 + i)),
            max_new_tokens=6 + (i % 3), sampling=sp))
    return out


def _serve(model, params, ecfg, requests, clock=CONST_CLOCK):
    eng = InferenceEngine(model, params, ecfg, clock=clock)
    for r in requests:
        eng.add_request(r)
    results = eng.run(return_status=True)
    return eng, results


# ---------------------------------------------------------------------------
# config validation (the ISSUE 15 "small fix" satellite)
# ---------------------------------------------------------------------------

def test_mesh_shape_validation_named_errors():
    for bad in ((0, 1), (1, 0), (1,), (1, 2, 3), "x1", (1.5, 2)):
        with pytest.raises(ValueError, match="mesh_shape"):
            _config(mesh_shape=bad)
    # more devices than the backend has (tests run on 8 virtual CPUs)
    with pytest.raises(ValueError, match="mesh_shape.*devices"):
        _config(mesh_shape=(2, 8))
    # a list normalizes to a tuple (fingerprint-stable)
    assert _config(mesh_shape=[1, 2]).mesh_shape == (1, 2)


def test_model_axis_must_divide_heads(tiny):
    cfg, model, params = tiny
    assert cfg.num_heads == 4
    with pytest.raises(ValueError, match="num_heads"):
        InferenceEngine(model, params, _config(mesh_shape=(1, 3)))
    with pytest.raises(ValueError, match="num_heads"):
        validate_mesh_shape((1, 8), num_heads=4)


def test_mesh_kwarg_must_match_config(tiny):
    _, model, params = tiny
    with pytest.raises(ValueError, match="mesh_shape"):
        InferenceEngine(model, params, _config(mesh_shape=(1, 2)),
                        mesh=build_mesh((1, 1)))


def test_pallas_flag_rejected_on_sharded_model_axis(tiny, monkeypatch):
    _, model, params = tiny
    monkeypatch.setenv("APEX_PAGED_ATTENTION_PALLAS", "1")
    with pytest.raises(ValueError, match="APEX_PAGED_ATTENTION_PALLAS"):
        InferenceEngine(model, params, _config(mesh_shape=(1, 2)))
    # a 1-sized model axis is single-device: the flag stays legal
    InferenceEngine(model, params, _config(mesh_shape=(1, 1)))


# ---------------------------------------------------------------------------
# mesh (1, 1) bit-identity to the pre-mesh engine
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("spec", [0, 2])
@pytest.mark.parametrize("quant", [None, "int8"])
def test_mesh11_bit_identity_matrix(tiny, monkeypatch, spec, quant):
    """THE promotion cert: the default (1, 1) mesh engine — programs
    compiled under the mesh, params/pool committed to (trivial)
    NamedShardings, out_shardings pinned — must reproduce the pre-mesh
    engine bit for bit: outputs, statuses, and the FULL stats() dict,
    greedy+sampled lanes, speculation on/off, int8 quantization on/off.
    The pre-mesh baseline is built by neutering the mesh layer (the
    exact byte-identical pre-PR code path: no device_put, no
    out_shardings)."""
    cfg, model, params = tiny
    reqs = _mixed_requests(cfg)
    ecfg = _config(kv_quantization=quant, spec_tokens=spec)
    mesh_eng, mesh_results = _serve(model, params, ecfg, reqs)

    monkeypatch.setattr(mesh_lib, "shard_params",
                        lambda mesh, params, pspec_fn=None: params)
    monkeypatch.setattr(mesh_lib, "shard_cache", lambda mesh, cache: cache)
    monkeypatch.setattr(mesh_lib, "program_out_shardings",
                        lambda mesh, cache: None)
    plain_eng, plain_results = _serve(model, params, ecfg, reqs)

    assert {u: r.tokens for u, r in mesh_results.items()} \
        == {u: r.tokens for u, r in plain_results.items()}
    assert {u: r.status for u, r in mesh_results.items()} \
        == {u: r.status for u, r in plain_results.items()}
    assert mesh_eng.stats() == plain_eng.stats()


# ---------------------------------------------------------------------------
# token-identity across mesh shapes + pinned compile counts
# ---------------------------------------------------------------------------

def test_cross_mesh_token_identity(tiny):
    """The same seeded trace at (1, 1), (1, 2) and (2, 2) must emit
    identical token streams and statuses: greedy argmaxes and the
    per-lane keyed draws are invariant to where the heads live (the
    all-reduce changes summation order by ulps, not verdicts — pinned
    here on fixed seeds, the same certified-per-backend posture as the
    speculative greedy cert)."""
    cfg, model, params = tiny
    reqs = _mixed_requests(cfg, n=6)
    baseline = None
    for shape in ((1, 1), (1, 2), (2, 2)):
        eng, results = _serve(model, params, _config(mesh_shape=shape),
                              reqs)
        got = {u: (r.tokens, r.status) for u, r in results.items()}
        assert eng.stats()["mesh_model_axis"] == shape[1]
        if baseline is None:
            baseline = got
        else:
            assert got == baseline, f"mesh {shape} diverged"


def test_mesh_compile_counts_pinned(tiny):
    """One prefill + one decode compilation for the engine's lifetime
    UNDER THE MESH: the out_shardings pin keeps the returned pool in
    the committed layout, so no second compile ever triggers — across
    multiple admission waves, block growth, and drained restarts."""
    cfg, model, params = tiny
    eng = InferenceEngine(model, params,
                          _config(mesh_shape=(1, 2), num_blocks=16,
                                  enable_prefix_caching=True))
    rr = np.random.RandomState(5)
    for wave in range(3):
        for i in range(4):
            eng.add_request(Request(
                uid=f"w{wave}r{i}",
                prompt=list(rr.randint(0, cfg.vocab_size, 5 + 2 * i)),
                max_new_tokens=7))
        eng.run()
    s = eng.stats()
    assert s["prefill_compilations"] == 1, s
    assert s["decode_compilations"] == 1, s


# ---------------------------------------------------------------------------
# the hlo_audit collective contract
# ---------------------------------------------------------------------------

def test_collective_contract_mesh11_zero(tiny):
    cfg, model, params = tiny
    eng = InferenceEngine(model, params, _config(mesh_shape=(1, 1)))
    audited = eng.audit_collectives()
    assert set(audited) == {"prefill", "decode"}
    for stats in audited.values():
        assert stats["total"]["ops"] == 0


def test_collective_contract_mesh12_allreduce(tiny):
    """Heads split -> the Megatron-via-GSPMD layout must show exactly
    the reduction traffic the layout predicts: one all-reduce per
    row-parallel projection (attn_out + mlp_out, per layer) in every
    program — prefill, decode scan, and speculative verify — and no
    all-to-all anywhere."""
    cfg, model, params = tiny
    eng = InferenceEngine(model, params, _config(mesh_shape=(1, 2)))
    audited = eng.audit_collectives()     # raises on contract violation

    def reductions(stats):
        # spelling-agnostic: XLA may lower one all-reduce as a
        # reduce-scatter + all-gather pair (the hlo_audit round-5
        # lesson); both satisfy the reduction contract
        return stats["all-reduce"]["ops"] + stats["reduce-scatter"]["ops"]

    for prog, stats in audited.items():
        assert reductions(stats) >= 2 * cfg.num_layers, (prog, stats)
        assert stats["all-to-all"]["ops"] == 0, (prog, stats)
    # the verify program (the decode slot under speculation) holds the
    # same contract
    spec_eng = InferenceEngine(model, params,
                               _config(mesh_shape=(1, 2), spec_tokens=3))
    audited = spec_eng.audit_collectives()
    assert "verify" in audited
    assert reductions(audited["verify"]) >= 2 * cfg.num_layers
    # and the audit's AOT lowering must not have perturbed the pinned
    # jit call caches
    assert eng.stats()["prefill_compilations"] == 0
    assert eng.stats()["decode_compilations"] == 0


def test_assert_collective_contract_unit():
    zero = collective_stats("")
    assert_collective_contract(zero, exact_total_ops=0)
    ar = collective_stats(
        "  %r = f32[8,16] all-reduce(f32[8,16] %x), replica_groups={}\n")
    with pytest.raises(AssertionError, match="exactly 0"):
        assert_collective_contract(ar, exact_total_ops=0)
    assert_collective_contract(ar, min_ops={"all-reduce": 1},
                               forbidden=("all-to-all",))
    with pytest.raises(AssertionError, match="floors"):
        assert_collective_contract(zero, min_ops={"all-reduce": 1})
    # the reduce-scatter + all-gather spelling satisfies the same
    # reduction contract through alt_min_ops
    rsag = collective_stats(
        "  %a = f32[4,16] reduce-scatter(f32[8,16] %x), dimensions={0}\n"
        "  %b = f32[8,16] all-gather(f32[4,16] %a), dimensions={0}\n")
    assert_collective_contract(rsag, min_ops={"all-reduce": 1},
                               alt_min_ops={"reduce-scatter": 1,
                                            "all-gather": 1})
    with pytest.raises(AssertionError, match="forbidden"):
        assert_collective_contract(
            collective_stats("  %c = f32[8] all-to-all(f32[8] %x)\n"),
            forbidden=("all-to-all",))


def test_expected_collectives_shapes():
    assert expected_collectives((1, 1)) == {"exact_total_ops": 0}
    assert expected_collectives((4, 1)) == {"exact_total_ops": 0}
    c = expected_collectives((1, 2))
    assert c["min_ops"] == {"all-reduce": 1}
    assert "all-to-all" in c["forbidden"]


# ---------------------------------------------------------------------------
# snapshot/restore + fleet identity with mesh-sharded engines
# ---------------------------------------------------------------------------

def test_mesh_snapshot_restore_bit_identity(tiny):
    """A (1, 2) engine snapshotted mid-run (JSON round-trip — the real
    wire) and restored into a fresh (1, 2) engine must finish
    bit-identically to the uninterrupted sharded run: the records are
    host-side and layout-free, and re-prefill re-derives the sharded
    pool."""
    cfg, model, params = tiny
    reqs = _mixed_requests(cfg, n=4)
    ecfg = _config(mesh_shape=(1, 2), enable_prefix_caching=True)
    _, uninterrupted = _serve(model, params, ecfg, reqs)

    eng = InferenceEngine(model, params, ecfg, clock=CONST_CLOCK)
    for r in reqs:
        eng.add_request(r)
    for _ in range(3):
        eng.step()
    snap = json.loads(json.dumps(eng.snapshot()))
    partial = eng.pop_results()

    restored = InferenceEngine(model, params, ecfg, clock=CONST_CLOCK)
    restored.restore(snap)
    finishing = restored.run(return_status=True)
    combined = {u: r.tokens for u, r in {**partial, **finishing}.items()}
    assert combined == {u: r.tokens for u, r in uninterrupted.items()}
    restored.check_allocator_integrity()


def test_mesh_shape_is_restore_identity(tiny):
    """mesh_shape joins the restore-fingerprint identity set: a
    (1, 1) snapshot refuses to restore into a (1, 2) engine (and the
    refusal names the knob) — but restores cleanly across EQUAL
    meshes, tuple-vs-JSON-list normalization included."""
    cfg, model, params = tiny
    eng = InferenceEngine(model, params, _config(mesh_shape=(1, 1)))
    snap = json.loads(json.dumps(eng.snapshot()))
    other = InferenceEngine(model, params, _config(mesh_shape=(1, 2)))
    with pytest.raises(ValueError, match="mesh_shape"):
        other.restore(snap)
    same = InferenceEngine(model, params, _config(mesh_shape=(1, 1)))
    same.restore(snap)      # tuple fingerprint == round-tripped list


def test_fleet_one_replica_mesh_identity(tiny):
    """The PR 12 fleet cert extended to sharded replicas: a 1-replica
    fleet whose engine is mesh-(1, 2)-sharded serves the trace
    identically to the bare (1, 2) engine (outputs + statuses), and
    the replica's allocator survives the run intact."""
    cfg, model, params = tiny
    reqs = _mixed_requests(cfg, n=5)
    ecfg = _config(mesh_shape=(1, 2), enable_prefix_caching=True)
    _, bare = _serve(model, params, ecfg, reqs)

    fleet = FleetRouter(model, params, ecfg,
                        FleetConfig(num_replicas=1), clock=CONST_CLOCK)
    for r in reqs:
        fleet.add_request(Request(
            uid=r.uid, prompt=list(r.prompt),
            max_new_tokens=r.max_new_tokens, sampling=r.sampling))
    fleet_results = fleet.run(return_status=True)
    assert {u: (r.tokens, r.status) for u, r in fleet_results.items()} \
        == {u: (r.tokens, r.status) for u, r in bare.items()}
    assert fleet.replicas[0].engine.config.mesh_shape == (1, 2)
    fleet.replicas[0].engine.check_allocator_integrity()


# ---------------------------------------------------------------------------
# mesh-sharded memory tiers + LRU churn
# ---------------------------------------------------------------------------

def test_mesh_spill_reserve_token_identity(tiny):
    """The host spill tier under a sharded pool: spilled payloads read
    out of (and upload back into) the mesh-sharded pools, and a
    flushed-then-re-served trace stays token-identical — the spill
    path is layout-free because payloads move as host numpy."""
    cfg, model, params = tiny
    from apex_tpu.serving import kv_block_bytes
    blk = kv_block_bytes(cfg.num_layers, 4, cfg.num_heads,
                         cfg.hidden_size // cfg.num_heads,
                         dtype=jnp.float32)
    ecfg = _config(mesh_shape=(1, 2), max_batch=2, num_blocks=8,
                   kv_dtype=jnp.float32, enable_prefix_caching=True,
                   spill_max_bytes=64 * blk)
    eng = InferenceEngine(model, params, ecfg)
    rr = np.random.RandomState(11)
    prompts = [list(rr.randint(0, cfg.vocab_size, 9)) for _ in range(3)]

    def serve(tag):
        for i, p in enumerate(prompts):
            eng.add_request(Request(uid=f"{tag}{i}", prompt=p,
                                    max_new_tokens=4))
        return eng.run()

    first = serve("a")
    eng.allocator.flush_evictable()
    second = serve("b")
    assert all(second[f"b{i}"] == first[f"a{i}"]
               for i in range(len(prompts)))
    s = eng.stats()
    assert s["spill_hits"] > 0, s
    eng.check_allocator_integrity()


def test_mesh_lru_churn_allocator_integrity(tiny):
    """check_allocator_integrity after mesh-sharded LRU churn: a tight
    pool, prefix caching, overlapping prompts, repeated waves —
    eviction, revival, preemption and CoW all run against the sharded
    pool, and the exact refcount/ledger audit must hold at the end."""
    cfg, model, params = tiny
    ecfg = _config(mesh_shape=(1, 2), num_blocks=12, max_batch=3,
                   enable_prefix_caching=True)
    eng = InferenceEngine(model, params, ecfg)
    rr = np.random.RandomState(13)
    shared = list(rr.randint(0, cfg.vocab_size, 8))
    for wave in range(3):
        for i in range(4):
            tail = list(rr.randint(0, cfg.vocab_size, 3 + i))
            eng.add_request(Request(uid=f"c{wave}_{i}",
                                    prompt=shared + tail,
                                    max_new_tokens=5))
        eng.run()
        eng.check_allocator_integrity()
    assert eng.stats()["prefix_hit_blocks"] > 0


# ---------------------------------------------------------------------------
# the folded tp=2 decode smoke (now a regular mesh test)
# ---------------------------------------------------------------------------

def test_tp2_paged_decode_mesh(tiny):
    """The old bespoke shard_map tp=2 smoke, folded into the mesh
    path: decode attention + the row-parallel output projection under
    NamedSharding annotations and plain jit — GSPMD inserts the
    Megatron psum itself (asserted from the compiled HLO), and the
    result matches the unsharded computation."""
    from apex_tpu.ops.flash_attention import paged_decode_attention

    B, H, D, N, bs, M = 2, 4, 8, 8, 4, 3
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, D).astype("f4"))
    k_pages = jnp.asarray(rng.randn(N, bs, H, D).astype("f4"))
    v_pages = jnp.asarray(rng.randn(N, bs, H, D).astype("f4"))
    w_out = jnp.asarray(rng.randn(H * D, 16).astype("f4") * 0.1)
    tables = jnp.asarray([[0, 2, 5], [1, 3, 4]], jnp.int32)
    ctx = jnp.asarray([9, 6], jnp.int32)
    scale = 1.0 / np.sqrt(D)

    def attend_project(q, kp, vp, w):
        out = paged_decode_attention(q, kp, vp, tables, ctx, scale)
        return out.reshape(B, -1) @ w       # GSPMD all-reduces this

    ref = attend_project(q, k_pages, v_pages, w_out)

    mesh = build_mesh((1, 2))
    shard = lambda x, spec: jax.device_put(      # noqa: E731
        x, NamedSharding(mesh, spec))
    jitted = jax.jit(attend_project,
                     out_shardings=NamedSharding(mesh, P()))
    args = (shard(q, P(None, "model")),
            shard(k_pages, P(None, None, "model")),
            shard(v_pages, P(None, None, "model")),
            # head-major flat rows: rank r's W_out rows stay contiguous
            shard(w_out, P("model", None)))
    got = jitted(*args)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)
    stats = collective_stats(jitted.lower(*args).compile().as_text())
    assert stats["all-reduce"]["ops"] >= 1 \
        or (stats["reduce-scatter"]["ops"] >= 1
            and stats["all-gather"]["ops"] >= 1), stats


# ---------------------------------------------------------------------------
# surface
# ---------------------------------------------------------------------------

def test_mesh_stats_and_fingerprint_surface(tiny):
    cfg, model, params = tiny
    eng = InferenceEngine(model, params, _config(mesh_shape=(1, 2)))
    s = eng.stats()
    assert s["mesh_devices"] == 2
    assert s["mesh_model_axis"] == 2
    fp = eng._config_fingerprint()
    assert fp["mesh_shape"] == [1, 2]       # JSON-stable list form
    assert tuple(eng.mesh.axis_names) == ("batch", "model")
