"""Fused norm kernels vs pure-jnp references (upstream analog:
tests/L0/run_fused_layer_norm — fused vs torch.nn.LayerNorm at
dtype-dependent tolerances, SURVEY.md §4)."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.normalization import (
    FusedLayerNorm,
    FusedRMSNorm,
    MixedFusedLayerNorm,
)
from apex_tpu.ops.layer_norm import (
    fused_layer_norm_affine,
    fused_rms_norm_affine,
    layer_norm_reference,
    rms_norm_reference,
)


def _data(shape, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(*shape).astype("float32")).astype(dtype)


@pytest.mark.parametrize("shape", [(8, 128), (4, 16, 256), (32, 512), (16, 100)])
def test_layer_norm_forward_matches_reference(shape):
    x = _data(shape)
    w = _data((shape[-1],), 1) * 0.1 + 1.0
    b = _data((shape[-1],), 2) * 0.1
    y, _ = jax.vjp(lambda x: fused_layer_norm_affine(x, w, b), x)
    ref = layer_norm_reference(x, w, b)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("shape", [(8, 128), (32, 384), (16, 100)])
def test_layer_norm_grads_match_reference(shape):
    x = _data(shape)
    w = _data((shape[-1],), 1) * 0.1 + 1.0
    b = _data((shape[-1],), 2) * 0.1

    def fused_loss(x, w, b):
        return jnp.sum(jnp.sin(fused_layer_norm_affine(x, w, b)))

    def ref_loss(x, w, b):
        return jnp.sum(jnp.sin(layer_norm_reference(x, w, b)))

    gf = jax.grad(fused_loss, argnums=(0, 1, 2))(x, w, b)
    gr = jax.grad(ref_loss, argnums=(0, 1, 2))(x, w, b)
    for a, r in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r), rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("shape", [(8, 128), (4, 8, 256), (16, 100)])
def test_rms_norm_forward_and_grads(shape):
    x = _data(shape)
    w = _data((shape[-1],), 1) * 0.1 + 1.0
    y = fused_rms_norm_affine(x, w)
    ref = rms_norm_reference(x, w)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-5)

    gf = jax.grad(lambda x, w: jnp.sum(jnp.sin(fused_rms_norm_affine(x, w))), (0, 1))(x, w)
    gr = jax.grad(lambda x, w: jnp.sum(jnp.sin(rms_norm_reference(x, w))), (0, 1))(x, w)
    for a, r in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r), rtol=2e-4, atol=2e-4)


def test_mixed_dtype_bf16_input_fp32_weight():
    """The MixedFusedLayerNorm contract: bf16 activations, fp32 params,
    fp32 internal math, bf16 output."""
    x = _data((16, 256), dtype=jnp.bfloat16)
    w = _data((256,), 1) * 0.1 + 1.0
    b = _data((256,), 2) * 0.1
    y, _ = jax.vjp(lambda x: fused_layer_norm_affine(x, w, b), x)
    assert y.dtype == jnp.bfloat16
    ref = layer_norm_reference(x, w, b)
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(ref, np.float32), rtol=2e-2, atol=2e-2
    )


def test_grads_flow_in_bf16():
    x = _data((8, 128), dtype=jnp.bfloat16)
    w = jnp.ones((128,), jnp.float32)
    b = jnp.zeros((128,), jnp.float32)
    dx, dw, db = jax.grad(
        lambda x, w, b: jnp.sum(fused_layer_norm_affine(x, w, b).astype(jnp.float32)),
        (0, 1, 2),
    )(x, w, b)
    assert dx.dtype == jnp.bfloat16
    assert dw.dtype == jnp.float32
    assert np.isfinite(np.asarray(dx, np.float32)).all()


def test_flax_module_surface():
    x = _data((4, 192))
    ln = FusedLayerNorm(normalized_shape=192)
    params = ln.init(jax.random.PRNGKey(0), x)
    assert params["params"]["scale"].shape == (192,)
    y = ln.apply(params, x)
    ref = layer_norm_reference(x, params["params"]["scale"], params["params"]["bias"])
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-5)

    rms = FusedRMSNorm(normalized_shape=192)
    p2 = rms.init(jax.random.PRNGKey(0), x)
    assert "bias" not in p2["params"]
    y2 = rms.apply(p2, x)
    assert y2.shape == x.shape


def test_no_affine_module():
    x = _data((4, 128))
    ln = FusedLayerNorm(normalized_shape=128, elementwise_affine=False)
    params = ln.init(jax.random.PRNGKey(0), x)
    assert not params.get("params")
    y = ln.apply(params, x)
    np.testing.assert_allclose(
        np.asarray(y),
        np.asarray(layer_norm_reference(x, jnp.ones((128,)), jnp.zeros((128,)))),
        rtol=1e-5, atol=1e-5,
    )


def test_mixed_module_keeps_fp32_params_under_bf16():
    x = _data((4, 128), dtype=jnp.bfloat16)
    ln = MixedFusedLayerNorm(normalized_shape=128)
    params = ln.init(jax.random.PRNGKey(0), x)
    assert params["params"]["scale"].dtype == jnp.float32
    y = ln.apply(params, x)
    assert y.dtype == jnp.bfloat16


def test_wrong_trailing_dim_raises():
    ln = FusedLayerNorm(normalized_shape=64)
    with pytest.raises(ValueError):
        ln.init(jax.random.PRNGKey(0), jnp.ones((4, 128)))


def test_under_jit_and_odd_rows():
    """Non-power-of-two row counts and jit compilation."""
    x = _data((17, 160))
    w = jnp.ones((160,))
    b = jnp.zeros((160,))
    # vjp so the Pallas training forward runs (the undifferentiated
    # primal is the jnp inference path since the mode-selection change)
    y, _ = jax.jit(lambda x: jax.vjp(
        lambda x: fused_layer_norm_affine(x, w, b), x))(x)
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(layer_norm_reference(x, w, b)), rtol=1e-5, atol=1e-5
    )


def test_large_prime_row_count_stays_block_tiled():
    """Row counts with no small divisor must still tile into bounded VMEM
    blocks (review regression: a (12291, H) single tile would not fit)."""
    from apex_tpu.ops.layer_norm import _block_rows, _round_up

    assert _block_rows(12291, 128) == 256
    x = _data((3, 4097, 128))  # 12291 rows
    w = jnp.ones((128,))
    b = jnp.zeros((128,))
    y, _ = jax.vjp(lambda x: fused_layer_norm_affine(x, w, b), x)
    assert y.shape == x.shape
    ref = layer_norm_reference(x, w, b)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-5)
    # grads through the padded-rows path
    gx = jax.grad(lambda x: jnp.sum(fused_layer_norm_affine(x, w, b)))(x)
    assert bool(jnp.all(jnp.isfinite(gx)))


def test_block_rows_shrink_for_wide_hidden():
    """Per-hidden-size tuning (the fast_layer_norm role): wide rows get
    smaller blocks so the fp32 tile stays ~2 MB; a regression that
    ignores hpad passes CPU-interpret tests but OOMs VMEM on hardware."""
    from apex_tpu.ops.layer_norm import _block_rows

    assert _block_rows(4096, 1024) == 256
    assert _block_rows(4096, 2048) == 256
    assert _block_rows(4096, 4096) == 128
    assert _block_rows(4096, 8192) == 64
    assert _block_rows(4096, 65536) == 8   # floor
    # wide-H functional path (interpret on CPU, compiled on TPU)
    x = _data((64, 8192))
    w = jnp.ones((8192,))
    b = jnp.zeros((8192,))
    y, _ = jax.vjp(lambda x: fused_layer_norm_affine(x, w, b), x)
    ref = layer_norm_reference(x, w, b)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5,
                               atol=1e-5)


def test_mode_dependent_selection_agrees():
    """The inference primal (XLA-fused jnp, docs/kernels.md measured
    default) and the training fwd (Pallas kernel) must agree numerically
    — the mode switch is a perf choice, not a semantics one."""
    import jax

    from apex_tpu.ops.layer_norm import (
        fused_layer_norm_affine,
        fused_rms_norm_affine,
    )

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(6, 640).astype("float32"))
    w = jnp.asarray(rng.randn(640).astype("float32"))
    b = jnp.asarray(rng.randn(640).astype("float32"))

    infer = fused_layer_norm_affine(x, w, b)          # primal body
    train, _ = jax.vjp(lambda x: fused_layer_norm_affine(x, w, b), x)
    np.testing.assert_allclose(np.asarray(infer), np.asarray(train),
                               rtol=1e-5, atol=1e-5)

    infer_r = fused_rms_norm_affine(x, w)
    train_r, _ = jax.vjp(lambda x: fused_rms_norm_affine(x, w), x)
    np.testing.assert_allclose(np.asarray(infer_r), np.asarray(train_r),
                               rtol=1e-5, atol=1e-5)


def test_multidim_normalized_shape_module():
    """apex parity: FusedLayerNorm((d1, d2)) normalizes over BOTH
    trailing dims and keeps params at the full normalized_shape
    (upstream apex/normalization/fused_layer_norm.py accepts tuples)."""
    import numpy as np

    from apex_tpu.normalization import FusedLayerNorm, FusedRMSNorm

    x = jnp.asarray(np.random.RandomState(0).randn(3, 4, 6).astype("f4"))
    m = FusedLayerNorm(normalized_shape=(4, 6))
    v = m.init(jax.random.PRNGKey(0), x)
    assert v["params"]["scale"].shape == (4, 6)
    assert v["params"]["bias"].shape == (4, 6)
    y = m.apply(v, x)
    assert y.shape == x.shape
    # matches normalizing the flattened trailing dims
    xf = x.reshape(3, 24)
    mean = xf.mean(-1, keepdims=True)
    var = ((xf - mean) ** 2).mean(-1, keepdims=True)
    ref = ((xf - mean) * jax.lax.rsqrt(var + 1e-5)).reshape(3, 4, 6)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=2e-5)

    r = FusedRMSNorm(normalized_shape=(4, 6))
    vr = r.init(jax.random.PRNGKey(0), x)
    assert vr["params"]["scale"].shape == (4, 6)
    yr = r.apply(vr, x)
    ms = (xf * xf).mean(-1, keepdims=True)
    refr = (xf * jax.lax.rsqrt(ms + 1e-5)).reshape(3, 4, 6)
    np.testing.assert_allclose(np.asarray(yr), np.asarray(refr), atol=2e-5)

    # grads flow through the reshaped path
    def loss(p):
        return jnp.sum(m.apply({"params": p}, x) ** 2)
    g = jax.grad(loss)(v["params"])
    assert g["scale"].shape == (4, 6)
    assert np.isfinite(np.asarray(g["scale"])).all()


def test_multidim_wrong_trailing_raises():
    from apex_tpu.normalization import FusedLayerNorm

    x = jnp.zeros((2, 3, 5))
    m = FusedLayerNorm(normalized_shape=(4, 5))
    try:
        m.init(jax.random.PRNGKey(0), x)
    except ValueError as e:
        assert "trailing" in str(e)
    else:
        raise AssertionError("expected ValueError")


def test_ln_fwd_mode_knob_is_live(monkeypatch):
    """APEX_TPU_LN_FWD is read per trace (round-5 review finding): the
    A/B knob must switch the training-forward implementation when set
    mid-process, not only at import. Observable: the all-Pallas fwd
    pads+slices through the kernel path while the xla fwd is the plain
    jnp formula — on oddly-shaped inputs both agree numerically, so the
    check is on the traced jaxpr instead."""
    from apex_tpu.ops.layer_norm import fused_layer_norm_affine

    x = jnp.ones((16, 64), jnp.float32)
    w = jnp.ones((64,), jnp.float32)
    b = jnp.zeros((64,), jnp.float32)

    def n_pallas_calls():
        return str(jax.make_jaxpr(
            jax.grad(lambda x: jnp.sum(
                fused_layer_norm_affine(x, w, b) ** 2)))(x)
        ).count("pallas_call")

    monkeypatch.setenv("APEX_TPU_LN_FWD", "pallas")
    assert n_pallas_calls() == 2, (
        "pallas mode: fwd AND bwd kernels in the grad jaxpr")
    monkeypatch.setenv("APEX_TPU_LN_FWD", "xla")
    assert n_pallas_calls() == 1, (
        "xla mode: the forward is the jnp formula, so only the bwd "
        "kernel remains")
