"""Disaggregated prefill/decode serving + the data-parallel batch
axis (tier-1, CPU, 8 virtual devices): the ISSUE 17 layer
(docs/fleet.md "Disaggregated roles", docs/serving.md "Mesh
sharding").

The certification matrix: a disaggregated fleet (prefill specialists
handing chain-hashed KV through the checksummed transport to decode
specialists) is token-identical to the colocated fleet, greedy +
sampled x speculation on/off; the two-stage router skips affinity
probes of prefill specialists during decode placement (counted);
handoffs survive the 'corrupt' fault kind at the transport sites
(refused -> recompute, token-identical, zero corrupt state admitted);
a dead prefill replica's in-flight work lands on survivors with zero
lost accepted requests; the autoscaler reads the PER-ROLE watermark
signal (a prefill backlog spawns a prefill specialist even while the
fleet-wide mean sits below the watermark). Plus the batch mesh axis:
``(2, 1)``/``(2, 2)`` token-identical to ``(1, 1)`` on fixed seeds,
``(1, 1)`` bit-identical run to run (full constant-clock stats),
compile counts pinned, the collective contract audited per shape, and
shard-residency allocator integrity after churn."""

import importlib.util
import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import GPTConfig, GPTLMHeadModel
from apex_tpu.observability import Observability
from apex_tpu.serving import (
    EngineConfig,
    FleetConfig,
    FleetRouter,
    InferenceEngine,
    Request,
    SamplingParams,
    validate_mesh_shape,
)
from apex_tpu.utils.faults import FaultPlan, FaultSpec

CONST_CLOCK = lambda: 0.0  # noqa: E731 — constant-clock stats compare


@pytest.fixture(scope="module")
def tiny_gpt():
    cfg = GPTConfig.tiny(dropout=0.0, remat=False)
    model = GPTLMHeadModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    return model, params


# the disaggregated-fleet engine geometry: prefix caching + the spill
# tier on (the handoff transport), max_seq_len long enough that no
# request is truncated mid-experiment
DISAGG_KW = dict(max_batch=4, block_size=4, num_blocks=32,
                 max_prefill_len=8, max_seq_len=48, decode_steps=2,
                 seed=7, enable_prefix_caching=True,
                 spill_max_bytes=1 << 20)

# the batch-axis engine geometry (mesh tests): max_batch/num_blocks
# divisible by every batch-axis size under test
MESH_KW = dict(max_batch=4, block_size=4, num_blocks=32,
               max_prefill_len=8, max_seq_len=32, decode_steps=2,
               seed=7)


def _fleet(tiny_gpt, n=2, fleet_kw=None, clock=CONST_CLOCK,
           faults=None, obs=None, **overrides):
    model, params = tiny_gpt
    kw = dict(DISAGG_KW)
    kw.update(overrides)
    return FleetRouter(model, params, EngineConfig(**kw),
                       FleetConfig(num_replicas=n, **(fleet_kw or {})),
                       clock=clock, faults=faults, obs=obs)


def _reqs(n=6, sampled=True, new=6, seed=3, uid="r"):
    """Seeded mixed workload: varied prompt lengths, greedy AND
    sampled lanes (per-request keys make the draws placement- and
    mesh-invariant)."""
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        prompt = list(rng.randint(1, 50, 7 + i))
        samp = (SamplingParams(temperature=0.7, top_k=8, top_p=0.9)
                if sampled and i % 2 else SamplingParams())
        out.append(Request(f"{uid}{i}", prompt,
                           max_new_tokens=new + (i % 3), sampling=samp))
    return out


def _resdict(res):
    return {u: (tuple(r.tokens), r.status) for u, r in res.items()}


def _run(fleet, reqs):
    for r in reqs:
        fleet.add_request(r)
    return fleet.run(return_status=True)


# ---------------------------------------------------------------------------
# role-config validation
# ---------------------------------------------------------------------------


def test_replica_roles_validation():
    with pytest.raises(ValueError, match="replica_roles"):
        FleetConfig(num_replicas=2, replica_roles=("prefill",))
    with pytest.raises(ValueError, match="replica_roles"):
        FleetConfig(num_replicas=2,
                    replica_roles=("prefill", "verifier"))
    # a disaggregated fleet needs BOTH specialist kinds
    with pytest.raises(ValueError, match="replica_roles"):
        FleetConfig(num_replicas=2,
                    replica_roles=("prefill", "prefill"))
    with pytest.raises(ValueError, match="replica_roles"):
        FleetConfig(num_replicas=2, replica_roles=("decode", "decode"))
    # a list normalizes to a tuple
    cfg = FleetConfig(num_replicas=2,
                      replica_roles=["prefill", "decode"])
    assert cfg.replica_roles == ("prefill", "decode")


def test_roles_require_prefix_caching(tiny_gpt):
    """The handoff rides the prefix-payload transport: roles without
    ``enable_prefix_caching`` have no handoff path and are refused at
    construction, not discovered as a silent colocated fallback."""
    model, params = tiny_gpt
    kw = dict(DISAGG_KW)
    kw.update(enable_prefix_caching=False, spill_max_bytes=None)
    with pytest.raises(ValueError, match="enable_prefix_caching"):
        FleetRouter(model, params, EngineConfig(**kw),
                    FleetConfig(num_replicas=2,
                                replica_roles=("prefill", "decode")))


# ---------------------------------------------------------------------------
# the disaggregation identity cert
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("spec", [0, 2])
def test_disagg_token_identical_to_colocated(tiny_gpt, spec):
    """THE handoff cert: a {1 prefill + 1 decode} disaggregated fleet
    reproduces the colocated 1-replica fleet token for token, status
    for status — greedy + sampled lanes, speculation on and off. The
    single router door preserves arrival order, so per-request PRNG
    identity holds; the handoff itself is the certified migrate path
    (drain -> checksummed export -> prefix-seeded import)."""
    co = _run(_fleet(tiny_gpt, n=1, spec_tokens=spec), _reqs())
    dis_fleet = _fleet(
        tiny_gpt, n=2, spec_tokens=spec,
        fleet_kw=dict(replica_roles=("prefill", "decode")))
    dis = _run(dis_fleet, _reqs())
    assert _resdict(dis) == _resdict(co)
    st = dis_fleet.stats()
    assert st["num_lost_requests"] == 0
    # work actually moved: the decode specialist did not sit idle
    assert st["num_handoffs"] > 0
    assert st["num_handoff_requests"] > 0
    assert st["num_handoff_bytes"] > 0
    assert st["replicas"]["0"]["role"] == "prefill"
    assert st["replicas"]["1"]["role"] == "decode"


def test_two_stage_router_skips_prefill_probes(tiny_gpt):
    """Satellite: during decode-stage placement the router never
    affinity-probes a prefill specialist — the skips are counted. A
    colocated fleet (no roles) probes everyone and counts zero."""
    dis = _fleet(tiny_gpt, n=2,
                 fleet_kw=dict(replica_roles=("prefill", "decode")))
    _run(dis, _reqs())
    assert dis.stats()["num_affinity_probes_skipped"] > 0

    co = _fleet(tiny_gpt, n=2)
    _run(co, _reqs())
    cs = co.stats()
    assert cs["num_affinity_probes_skipped"] == 0
    # the colocated fleet keeps the pre-role surface quiet: no
    # handoffs, every replica the single "mixed" role
    assert cs["num_handoffs"] == 0
    assert cs["num_handoff_bytes"] == 0
    assert all(r["role"] == "mixed" for r in cs["replicas"].values())


def test_handoff_survives_corrupt_transport(tiny_gpt):
    """Handoff under the 'corrupt' fault kind at the transport site:
    a rotted export is REFUSED at the decode specialist's import
    verify, the request re-enters fresh at the source (recompute), and
    the fleet output stays token-identical to the colocated run —
    corrupt state never re-enters, correctness never depends on the
    transport staying clean."""
    co = _run(_fleet(tiny_gpt, n=1), _reqs())
    faults = [FaultPlan([FaultSpec(site="export", kind="corrupt",
                                   every=2)]),
              None]
    dis_fleet = _fleet(
        tiny_gpt, n=2, faults=faults,
        fleet_kw=dict(replica_roles=("prefill", "decode")))
    dis = _run(dis_fleet, _reqs())
    assert _resdict(dis) == _resdict(co)
    st = dis_fleet.stats()
    assert st["num_refused_imports"] > 0, \
        "the corrupt fault never fired at the handoff transport"
    assert st["num_lost_requests"] == 0


def test_role_aware_failover_zero_lost(tiny_gpt):
    """Kill the only prefill specialist mid-trace: its in-handoff and
    still-prefilling requests land on survivors (zero-lost outranks
    specialization — the survivor pool falls back to every alive
    replica when a role group empties) and every accepted request
    reaches a terminal status."""
    fleet = _fleet(
        tiny_gpt, n=3,
        fleet_kw=dict(replica_roles=("prefill", "decode", "decode")))
    reqs = _reqs()
    for r in reqs:
        fleet.add_request(r)
    fleet.step()
    fleet.kill_replica(0)
    res = fleet.run(return_status=True)
    st = fleet.stats()
    assert st["num_lost_requests"] == 0
    assert set(res) == {r.uid for r in reqs}
    assert all(v.status in ("finished", "aborted") for v in res.values())


# ---------------------------------------------------------------------------
# the per-role autoscaler signal (satellite)
# ---------------------------------------------------------------------------


def test_autoscaler_reads_per_role_watermark(tiny_gpt):
    """A prefill backlog behind an idle decode specialist: the
    fleet-wide mean queue depth sits BELOW the high watermark (the
    pre-role signal would never fire) while the prefill-role mean sits
    above it — the autoscaler must spawn, and spawn a PREFILL
    specialist."""
    obs = Observability(trace=False, metrics=False)
    fleet = _fleet(
        tiny_gpt, n=2, obs=obs,
        fleet_kw=dict(replica_roles=("prefill", "decode"),
                      autoscale_high_watermark=4.0,
                      autoscale_patience=1,
                      autoscale_max_replicas=3))
    for r in _reqs(n=12, sampled=False):
        fleet.add_request(r)
    # every request queues at the one prefill specialist: prefill-role
    # mean ~ 12 > 4.0 while the fleet-wide mean ~ 6 ... still above;
    # step once so the drained depth (what the signal reads) settles
    fleet.step()
    st = fleet.stats()
    assert st["num_spawned"] >= 1, "the per-role signal never fired"
    spawns = [e for e in obs.recorder.tail()
              if e["kind"] == "replica_spawn"]
    assert spawns and all(e["role"] == "prefill" for e in spawns)
    roles = [r["role"] for r in st["replicas"].values()]
    assert roles.count("decode") == 1, \
        "the idle decode role must not have scaled"
    fleet.run()
    assert fleet.stats()["num_lost_requests"] == 0


def test_colocated_autoscaler_unchanged(tiny_gpt):
    """No roles -> the single 'mixed' group IS the pre-role signal:
    the scalar streak attributes keep their exact meaning and a quiet
    fleet never scales."""
    fleet = _fleet(tiny_gpt, n=1,
                   fleet_kw=dict(autoscale_high_watermark=100.0,
                                 autoscale_patience=2,
                                 autoscale_max_replicas=2))
    _run(fleet, _reqs(n=3))
    assert fleet.stats()["num_spawned"] == 0
    assert fleet._autoscale_hi_streak == 0


# ---------------------------------------------------------------------------
# the observability surface
# ---------------------------------------------------------------------------


def test_handoff_recorder_events_and_trace_summary(tiny_gpt, tmp_path):
    obs = Observability(trace=False, metrics=False)
    fleet = _fleet(
        tiny_gpt, n=2, obs=obs,
        fleet_kw=dict(replica_roles=("prefill", "decode")))
    _run(fleet, _reqs())
    evs = [e for e in obs.recorder.tail()
           if e["kind"] == "prefill_handoff"]
    assert evs, "no prefill_handoff events recorded"
    for e in evs:
        assert e["src"] == 0
        assert e["requests"] > 0
        assert e["bytes"] > 0
        assert "prefill_queue" in e and "decode_queue" in e

    dump_path = tmp_path / "disagg_dump.json"
    dump_path.write_text(json.dumps(obs.dump(), default=str))
    spec = importlib.util.spec_from_file_location(
        "_trace_summary",
        Path(__file__).resolve().parents[1] / "tools" /
        "trace_summary.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    report = mod.summarize_file(str(dump_path))
    assert "-- disaggregation:" in report
    assert "prefill->decode" in report


# ---------------------------------------------------------------------------
# the batch mesh axis (tentpole (a) certification matrix)
# ---------------------------------------------------------------------------


def _mesh_engine(tiny_gpt, mesh_shape, **overrides):
    model, params = tiny_gpt
    kw = dict(MESH_KW)
    kw.update(overrides)
    return InferenceEngine(model, params,
                           EngineConfig(mesh_shape=mesh_shape, **kw),
                           clock=CONST_CLOCK)


def _mesh_serve(tiny_gpt, mesh_shape, reqs, **overrides):
    eng = _mesh_engine(tiny_gpt, mesh_shape, **overrides)
    for r in reqs:
        eng.add_request(r)
    return eng, eng.run(return_status=True)


def test_batch_axis_divisibility_validation():
    with pytest.raises(ValueError, match="max_batch"):
        validate_mesh_shape((3, 1), max_batch=4)
    with pytest.raises(ValueError, match="num_blocks"):
        validate_mesh_shape((2, 1), max_batch=4, num_blocks=31)
    kw = dict(MESH_KW)
    kw["max_batch"] = 6
    with pytest.raises(ValueError, match="max_batch"):
        EngineConfig(mesh_shape=(4, 1), **kw)
    kw = dict(MESH_KW)
    kw["num_blocks"] = 30
    with pytest.raises(ValueError, match="num_blocks"):
        EngineConfig(mesh_shape=(4, 1), **kw)


@pytest.mark.parametrize("spec", [0, 2])
def test_batch_mesh11_bit_identity(tiny_gpt, spec):
    """The batch axis at size 1 is the unsharded engine, byte for
    byte: two (1, 1) runs under the constant clock agree on outputs,
    statuses, and the FULL stats() dict — the baseline every
    cross-mesh comparison below leans on."""
    a_eng, a = _mesh_serve(tiny_gpt, (1, 1), _reqs(n=5),
                           spec_tokens=spec)
    b_eng, b = _mesh_serve(tiny_gpt, (1, 1), _reqs(n=5),
                           spec_tokens=spec)
    assert _resdict(a) == _resdict(b)
    assert a_eng.stats() == b_eng.stats()
    assert a_eng.stats()["mesh_batch_axis"] == 1


@pytest.mark.parametrize("mesh", [(2, 1), (2, 2)],
                         ids=["b2m1", "b2m2"])
@pytest.mark.parametrize("spec", [0, 2])
def test_batch_axis_cross_mesh_token_identity(tiny_gpt, mesh, spec):
    """THE batch-axis cert: splitting decode lanes, block tables, and
    the KV pool's lane/block dimension over the ``batch`` axis — alone
    at (2, 1), combined with the Megatron head split at (2, 2) —
    reproduces the (1, 1) token streams exactly on fixed seeds,
    greedy + sampled, speculation on and off; compile counts stay
    pinned at one per program; the collective contract holds (the
    batch axis lowers ZERO new collectives); and after the run every
    resident's blocks live on its lane's shard."""
    reqs = _reqs(n=5)
    _, base = _mesh_serve(tiny_gpt, (1, 1), reqs, spec_tokens=spec)
    eng, out = _mesh_serve(tiny_gpt, mesh, reqs, spec_tokens=spec)
    assert _resdict(out) == _resdict(base)
    st = eng.stats()
    assert st["mesh_batch_axis"] == mesh[0]
    assert st["mesh_model_axis"] == mesh[1]
    assert st["prefill_compilations"] == 1
    assert st["decode_compilations"] == 1
    eng.audit_collectives()
    eng.check_allocator_integrity()


def test_batch_axis_multiplies_concurrency(tiny_gpt):
    """What the axis is FOR: at (2, 1) with max_batch=4 each shard
    owns 2 lanes and half the pool — the engine still admits and
    finishes a workload deeper than one shard's lane count, and the
    shard-residency invariant holds through the churn."""
    reqs = _reqs(n=8, sampled=False, new=4)
    eng, out = _mesh_serve(tiny_gpt, (2, 1), reqs)
    assert len(out) == 8
    assert all(v.status == "finished" for v in out.values())
    eng.check_allocator_integrity()


def test_disagg_fleet_on_batch_sharded_engines(tiny_gpt):
    """The two tentpoles composed: a disaggregated fleet whose every
    replica runs a (2, 1) batch-sharded engine is token-identical to
    the colocated (1, 1) single-replica fleet."""
    model, params = tiny_gpt
    kw = dict(DISAGG_KW)

    def fleet_for(mesh, n, roles):
        return FleetRouter(
            model, params, EngineConfig(mesh_shape=mesh, **kw),
            FleetConfig(num_replicas=n, replica_roles=roles),
            clock=CONST_CLOCK)

    co = _run(fleet_for((1, 1), 1, None), _reqs())
    dis_fleet = fleet_for((2, 1), 2, ("prefill", "decode"))
    dis = _run(dis_fleet, _reqs())
    assert _resdict(dis) == _resdict(co)
    st = dis_fleet.stats()
    assert st["num_handoffs"] > 0
    assert st["num_lost_requests"] == 0
    for rep in dis_fleet.replicas:
        if rep.alive and rep.engine is not None:
            rep.engine.check_allocator_integrity()
