"""ResNet NHWC tests: shapes, train smoke with DDP-style data
parallelism + cross-replica BN on the 8-device mesh (the BASELINE
configs[3] correctness analog)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
from apex_tpu.models import ResNet, ResNetConfig
from apex_tpu.optimizers import FusedSGD


@pytest.mark.slow
def test_resnet50_shapes():
    cfg = ResNetConfig.resnet50(num_classes=10)
    model = ResNet(cfg)
    x = jnp.ones((1, 64, 64, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (1, 10)
    # 50-layer structure: stem + 3+4+6+3 bottlenecks x 3 convs + fc
    n_convs = sum(1 for p in jax.tree_util.tree_leaves_with_path(
        variables["params"]) if "conv" in str(p[0]).lower())
    assert n_convs >= 49


@pytest.mark.slow
def test_resnet_train_smoke_tiny():
    cfg = ResNetConfig.tiny()
    model = ResNet(cfg)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(8, 16, 16, 3).astype("f4"))
    y = jnp.asarray(rng.randint(0, 10, 8))
    variables = model.init(jax.random.PRNGKey(0), x)
    params, bstats = variables["params"], variables["batch_stats"]
    opt = FusedSGD(lr=0.1, momentum=0.9)
    state = opt.init(params)

    @jax.jit
    def step(params, bstats, state):
        def loss_fn(p):
            logits, mut = model.apply(
                {"params": p, "batch_stats": bstats}, x, train=True,
                mutable=["batch_stats"])
            loss = jnp.mean(softmax_cross_entropy_loss(
                logits, y, padding_idx=-1))
            return loss, mut["batch_stats"]

        (loss, new_bstats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        params, state = opt.step(grads, state, params)
        return params, new_bstats, state, loss

    losses = []
    for _ in range(10):
        params, bstats, state, loss = step(params, bstats, state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8


@pytest.mark.slow
def test_resnet_dp_syncbn_on_mesh():
    """Data-parallel ResNet with bn_group spanning the mesh: per-device
    batches, synced BN stats, psum'd grads — one train step runs and the
    BN running stats agree across replicas."""
    cfg = ResNetConfig.tiny(bn_group=8, axis_name="data")
    model = ResNet(cfg)
    mesh = jax.make_mesh((8,), ("data",))
    rng = np.random.RandomState(0)
    X = jnp.asarray(rng.randn(16, 8, 8, 3).astype("f4"))
    Y = jnp.asarray(rng.randint(0, 10, 16))

    def step(X_local, Y_local):
        variables = model.init(jax.random.PRNGKey(0), X_local, train=False)
        params, bstats = variables["params"], variables["batch_stats"]

        def loss_fn(p):
            logits, mut = model.apply(
                {"params": p, "batch_stats": bstats}, X_local, train=True,
                mutable=["batch_stats"])
            loss = jnp.mean(softmax_cross_entropy_loss(
                logits, Y_local, padding_idx=-1))
            return loss, mut["batch_stats"]

        (loss, new_bstats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        grads = jax.tree.map(lambda g: jax.lax.pmean(g, "data"), grads)
        loss = jax.lax.pmean(loss, "data")
        gn = jnp.sqrt(sum(jnp.sum(g * g) for g in jax.tree.leaves(grads)))
        stem_mean = new_bstats["bn_stem"]["running_mean"]
        return loss[None], gn[None], stem_mean[None]

    loss, gn, stem_means = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P("data"), P("data")),
        out_specs=(P("data"), P("data"), P("data"))))(X, Y)
    assert np.isfinite(np.asarray(loss)).all()
    assert float(np.asarray(gn)[0]) > 0
    # synced BN: every replica computed the SAME running stats
    sm = np.asarray(stem_means)
    np.testing.assert_allclose(sm, np.broadcast_to(sm[:1], sm.shape),
                               rtol=1e-5, atol=1e-6)
