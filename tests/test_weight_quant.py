"""Quantized weight storage certification (tier-1, CPU): the ISSUE 19
layer (docs/serving.md "Quantized weight storage").

The quantization transform (per-output-channel scales, deterministic
bytes, the byte shrink, idempotency); the fused Pallas dequant-GEMM
certified BIT-IDENTICAL to the XLA dequantize-then-dot reference in
interpret mode (tiled and single-tile shapes, decode row counts
included); quantized logits at tight tolerance to fp; engine greedy
decode token-identical across ``weight_quantization`` on/off with
speculation on/off; the restore-fingerprint refusal across mismatched
modes; the process-replica params-checksum handshake covering the
quantized representation; scale sharding on the ``model`` axis (the
(1, 1) bit-identity + cross-mesh token-identity matrix, pinned compile
counts, the hlo_audit collective contract); the env-flag gate at a
sharded model axis; the labeled quantization-mode gauges; and the
``dequant_gemm`` recorder event surfaced by ``tools/trace_summary.py``.
"""

import importlib.util
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.models import GPTConfig, GPTLMHeadModel
from apex_tpu.models.gpt import (
    WEIGHT_QUANT_MODES,
    fp8_weight_dtype,
    gpt_param_bytes,
    gpt_param_pspec,
    quantize_dense_kernel,
    quantize_gpt_params,
    quantize_gpt_model,
)
from apex_tpu.observability import QUANT_MODE_CODES, Observability
from apex_tpu.ops import dequant_gemm as dg
from apex_tpu.serving import (
    EngineConfig,
    InferenceEngine,
    ProcessReplica,
    Request,
    SamplingParams,
)
from apex_tpu.serving import mesh as mesh_lib
from apex_tpu.serving.process_replica import (
    gpt_model_spec,
    params_checksum,
)
from apex_tpu.utils.integrity import IntegrityError

CONST_CLOCK = lambda: 0.0  # noqa: E731 — constant-clock stats compare

QUANT_MODES = ["int8"] + (["fp8"] if fp8_weight_dtype() is not None
                          else [])


@pytest.fixture(scope="module")
def tiny():
    cfg = GPTConfig.tiny(dropout=0.0, remat=False)
    model = GPTLMHeadModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    return cfg, model, params


def _config(**kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 32)
    kw.setdefault("max_prefill_len", 8)
    kw.setdefault("max_seq_len", 32)
    kw.setdefault("seed", 7)
    return EngineConfig(**kw)


def _requests(cfg, n=5, sampled=False, seed=3):
    rr = np.random.RandomState(seed)
    out = []
    for i in range(n):
        sp = (SamplingParams(temperature=0.7, top_k=8, top_p=0.9)
              if sampled and i % 2 else SamplingParams())
        out.append(Request(
            uid=f"r{i}", prompt=list(rr.randint(0, cfg.vocab_size, 6 + i)),
            max_new_tokens=6, sampling=sp))
    return out


def _serve(model, params, ecfg, requests, **kw):
    eng = InferenceEngine(model, params, ecfg, clock=CONST_CLOCK, **kw)
    for r in requests:
        eng.add_request(r)
    return eng, eng.run()


# ---------------------------------------------------------------------------
# the quantization transform
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", QUANT_MODES)
def test_quantize_dense_kernel_shape_dtype_determinism(mode):
    rr = np.random.RandomState(0)
    w = jnp.asarray(rr.randn(16, 12), jnp.float32)
    q1, s1 = quantize_dense_kernel(w, mode)
    q2, s2 = quantize_dense_kernel(w, mode)
    assert q1.shape == (16, 12) and s1.shape == (12,)
    assert s1.dtype == jnp.float32
    assert q1.dtype != jnp.float32
    # deterministic bytes — what lets the checksum handshake cover
    # the quantized representation
    assert np.array_equal(np.asarray(q1), np.asarray(q2))
    assert np.array_equal(np.asarray(s1), np.asarray(s2))
    # round trip lands near the fp kernel: int8 has 2^7 symmetric
    # steps per column; fp8 e4m3's 3-bit mantissa is coarser
    back = np.asarray(q1, np.float32) * np.asarray(s1)[None, :]
    amax = float(np.abs(np.asarray(w)).max())
    bound = amax / (64.0 if mode == "int8" else 8.0)
    assert np.abs(back - np.asarray(w)).max() <= bound


def test_quantize_gpt_params_tree_and_bytes(tiny):
    _, _, params = tiny
    q = quantize_gpt_params(params, "int8")
    blocks = q["params"]["transformer"]["h_0"]
    for module in ("attn_q", "attn_k", "attn_v", "attn_out",
                   "mlp_in", "mlp_out"):
        rec = blocks[module]
        assert rec["kernel"].dtype == jnp.int8
        assert rec["scale"].dtype == jnp.float32
        assert rec["scale"].shape == (rec["kernel"].shape[1],)
        assert rec["bias"].dtype == jnp.float32
    # embeddings / norms pass through untouched
    assert q["params"]["transformer"]["wte"].dtype == jnp.float32
    # the memory win the whole PR exists for: >= 1.8x fewer bytes
    assert gpt_param_bytes(params) / gpt_param_bytes(q) >= 1.8


def test_quantize_gpt_model_idempotent_and_remode_refused(tiny):
    _, model, params = tiny
    qmodel, qparams = quantize_gpt_model(model, params, "int8")
    assert qmodel.cfg.weight_quantization == "int8"
    # same mode on already-quantized storage: identity (re-quantizing
    # int8 bytes would corrupt them)
    m2, p2 = quantize_gpt_model(qmodel, qparams, "int8")
    assert m2 is qmodel and p2 is qparams
    with pytest.raises(ValueError, match="re-quantize"):
        quantize_gpt_model(qmodel, qparams, "fp8")
    with pytest.raises(ValueError, match="weight_quantization"):
        quantize_gpt_model(model, params, "int4")
    # mode=None is the identity
    assert quantize_gpt_model(model, params, None) == (model, params)


def test_scale_leaves_shard_like_their_module(tiny):
    """The PR 11 colocate-scales-with-bytes rule applied to weights:
    a quantized kernel's per-output-channel scales take the SAME
    model-axis placement as the output dim of their kernel —
    column-parallel scales shard, row-parallel scales replicate."""
    _, _, params = tiny
    q = quantize_gpt_params(params, "int8")
    specs = {}
    def visit(path, leaf):
        names = [str(getattr(p, "key", p)) for p in path]
        if names[-1] == "scale":
            specs[tuple(names[-2:])] = gpt_param_pspec(path)
        return leaf
    jax.tree_util.tree_map_with_path(visit, q)
    assert specs[("attn_q", "scale")] == P("model")
    assert specs[("mlp_in", "scale")] == P("model")
    assert specs[("attn_out", "scale")] == P()
    assert specs[("mlp_out", "scale")] == P()


# ---------------------------------------------------------------------------
# the fused Pallas dequant-GEMM: bit-identity to the XLA reference
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", QUANT_MODES)
@pytest.mark.parametrize("m,k,n", [
    (1, 64, 256),      # decode row, tiled N (2 x 128 lanes)
    (8, 128, 128),     # aligned everything, single tile
    (4, 48, 96),       # unaligned single-tile fallback shape
])
def test_pallas_dequant_gemm_bit_identical(mode, m, k, n):
    """THE kernel cert: N-only tiling leaves every output column's
    K-reduction order untouched, so the fused kernel must reproduce
    the XLA dequantize-then-dot reference BIT for bit (interpret mode
    on CPU), decode (single-row) shapes included."""
    rr = np.random.RandomState(7)
    x = jnp.asarray(rr.randn(m, k), jnp.float32)
    w = jnp.asarray(rr.randn(k, n), jnp.float32)
    w_q, scale = quantize_dense_kernel(w, mode)
    ref = dg.dequant_matmul_reference(x, w_q, scale)
    fused = dg.dequant_matmul(x, w_q, scale, use_pallas=True)
    assert np.array_equal(np.asarray(ref), np.asarray(fused))


def test_dequant_matmul_default_is_reference(monkeypatch):
    """Flag off -> the universal XLA fallback, byte-for-byte."""
    monkeypatch.delenv(dg._ENV_FLAG, raising=False)
    assert not dg.dequant_gemm_wanted()
    monkeypatch.setenv(dg._ENV_FLAG, "1")
    assert dg.dequant_gemm_wanted()
    assert not dg.dequant_gemm_wanted(use_pallas=False)
    rr = np.random.RandomState(1)
    x = jnp.asarray(rr.randn(2, 3, 32), jnp.float32)   # leading dims fold
    w_q, scale = quantize_dense_kernel(
        jnp.asarray(rr.randn(32, 64), jnp.float32), "int8")
    out = dg.dequant_matmul(x, w_q, scale, use_pallas=False)
    ref = dg.dequant_matmul_reference(
        x.reshape(-1, 32), w_q, scale).reshape(2, 3, 64)
    assert out.shape == (2, 3, 64)
    assert np.array_equal(np.asarray(out), np.asarray(ref))


# ---------------------------------------------------------------------------
# quantized logits + engine decode identity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", QUANT_MODES)
def test_quantized_logits_close_to_fp(tiny, mode):
    cfg, model, params = tiny
    qmodel, qparams = quantize_gpt_model(model, params, mode)
    tokens = jnp.asarray(
        np.random.RandomState(2).randint(0, cfg.vocab_size, (2, 12)))
    fp = model.apply(params, tokens, deterministic=True)
    q = qmodel.apply(qparams, tokens, deterministic=True)
    assert q.shape == fp.shape
    np.testing.assert_allclose(np.asarray(q), np.asarray(fp),
                               rtol=0.15, atol=0.15)


@pytest.mark.parametrize("spec", [0, 2])
@pytest.mark.parametrize("mode", QUANT_MODES)
def test_engine_greedy_token_identity_across_modes(tiny, mode, spec):
    """Greedy decode is argmax over logits whose quantization error is
    far below the argmax margins on the test seeds: the quantized
    engine must emit the EXACT fp token streams, speculation on or
    off — and the sampled lanes must run to completion under the same
    per-lane keyed draws."""
    cfg, model, params = tiny
    reqs = _requests(cfg, sampled=True)
    _, fp_out = _serve(model, params, _config(spec_tokens=spec), reqs)
    qeng, q_out = _serve(model, params,
                         _config(spec_tokens=spec,
                                 weight_quantization=mode), reqs)
    greedy = [r.uid for r in reqs
              if r.sampling.temperature == 0.0]
    assert greedy, "matrix needs greedy lanes"
    for uid in greedy:
        assert q_out[uid] == fp_out[uid], uid
    assert set(q_out) == set(fp_out)          # sampled lanes finished
    st = qeng.stats()
    assert st["weight_quantization"] == mode
    assert st["kv_quantization"] is None


def test_engine_rejects_unknown_mode():
    with pytest.raises(ValueError, match="weight_quantization"):
        _config(weight_quantization="int4")


def test_fingerprint_refuses_mismatched_mode(tiny):
    """IDENTITY: quantized storage is a different numerical program,
    so a snapshot taken under one mode must not restore into an
    engine running another."""
    _, model, params = tiny
    fp_eng = InferenceEngine(model, params, _config())
    snap = fp_eng.snapshot()
    q_eng = InferenceEngine(model, params,
                            _config(weight_quantization="int8"))
    with pytest.raises(ValueError, match="config mismatch"):
        q_eng.restore(snap)
    # matched mode round-trips
    q2 = InferenceEngine(model, params,
                         _config(weight_quantization="int8"))
    q2.restore(q_eng.snapshot())


# ---------------------------------------------------------------------------
# mesh matrix: scale sharding under the model axis
# ---------------------------------------------------------------------------

def test_quant_mesh11_bit_identity(tiny, monkeypatch):
    """The (1, 1) mesh engine with quantized weights reproduces the
    meshless quantized engine bit for bit (a 1-partition SPMD program
    is the unpartitioned program — scales included)."""
    cfg, model, params = tiny
    reqs = _requests(cfg)
    ecfg = _config(weight_quantization="int8")
    mesh_eng, mesh_out = _serve(model, params, ecfg, reqs)
    monkeypatch.setattr(mesh_lib, "shard_params",
                        lambda mesh, params, pspec_fn=None: params)
    monkeypatch.setattr(mesh_lib, "shard_cache", lambda mesh, cache: cache)
    monkeypatch.setattr(mesh_lib, "program_out_shardings",
                        lambda mesh, cache: None)
    plain_eng, plain_out = _serve(model, params, ecfg, reqs)
    assert mesh_out == plain_out
    assert mesh_eng.stats() == plain_eng.stats()


def test_quant_cross_mesh_token_identity_and_contract(tiny):
    """(1, 1) / (2, 1) / (1, 2) with int8 weights: identical token
    streams, compile counts pinned at one per program, and the
    collective contract holding with the sharded scale leaves in the
    weights (zero collectives at a 1-sized model axis; audited
    all-reduce-only traffic once heads split)."""
    cfg, model, params = tiny
    reqs = _requests(cfg, n=4)
    baseline = None
    for shape in ((1, 1), (2, 1), (1, 2)):
        eng, out = _serve(model, params,
                          _config(mesh_shape=shape,
                                  weight_quantization="int8"), reqs)
        if baseline is None:
            baseline = out
        else:
            assert out == baseline, f"mesh {shape} diverged"
        s = eng.stats()
        assert s["prefill_compilations"] == 1, s
        assert s["decode_compilations"] == 1, s
        audited = eng.audit_collectives()   # raises on violation
        if shape[1] == 1:
            assert all(v["total"]["ops"] == 0 for v in audited.values())


def test_dequant_flag_rejected_on_sharded_model_axis(tiny, monkeypatch):
    _, model, params = tiny
    monkeypatch.setenv(dg._ENV_FLAG, "1")
    with pytest.raises(ValueError, match="APEX_DEQUANT_GEMM_PALLAS"):
        InferenceEngine(model, params,
                        _config(mesh_shape=(1, 2),
                                weight_quantization="int8"))
    # a 1-sized model axis is single-device: the flag stays legal
    InferenceEngine(model, params,
                    _config(weight_quantization="int8"))


# ---------------------------------------------------------------------------
# process-replica handshake: the checksum covers the quantized bytes
# ---------------------------------------------------------------------------

def test_params_checksum_covers_quantized_representation(tiny):
    _, _, params = tiny
    base = params_checksum(params)
    q = params_checksum(params, weight_quantization="int8")
    assert base != q
    # deterministic across calls (round-to-nearest, no stochasticity)
    assert q == params_checksum(params, weight_quantization="int8")
    if fp8_weight_dtype() is not None:
        assert q != params_checksum(params, weight_quantization="fp8")


def test_process_replica_weight_quant_handshake(tiny):
    """A child booted with a MATCHING weight_quantization mode passes
    the hello handshake and serves; a parent expectation computed
    under a different mode is refused at hello — the mismatched-mode
    boot can never serve different-numerics logits behind an
    "equal weights" handshake."""
    cfg, _, params = tiny
    ecfg = _config(max_batch=2, weight_quantization="int8")
    good = params_checksum(params, weight_quantization="int8")
    rep = ProcessReplica(ecfg, gpt_model_spec(cfg),
                         expect_params_checksum=good)
    try:
        rep.add_request(Request(uid="q0", prompt=[1, 2, 3],
                                max_new_tokens=3))
        out, n = {}, 0
        while rep.has_work and n < 60:
            rep.step()
            out.update(rep.pop_results())
            n += 1
        out.update(rep.pop_results())
        assert out["q0"].status == "finished"
    finally:
        rep.close()
    # fp expectation vs int8 child: refused at hello
    with pytest.raises(IntegrityError, match="checksum"):
        ProcessReplica(ecfg, gpt_model_spec(cfg),
                       expect_params_checksum=params_checksum(params))


# ---------------------------------------------------------------------------
# observability: labeled mode gauges + the recorder event
# ---------------------------------------------------------------------------

def test_quant_mode_gauges_and_recorder_event(tiny):
    cfg, model, params = tiny
    obs = Observability(clock=CONST_CLOCK)
    eng, _ = _serve(model, params,
                    _config(weight_quantization="int8",
                            kv_quantization="int8"),
                    _requests(cfg, n=2), obs=obs)
    expo = obs.metrics.exposition()
    assert 'serving_quantization_mode{kind="kv"} 1' in expo
    assert 'serving_quantization_mode{kind="weight"} 1' in expo
    # one family header for the two labeled members
    assert expo.count("# TYPE serving_quantization_mode gauge") == 1
    assert QUANT_MODE_CODES[None] == 0.0
    evs = [e for e in obs.recorder.dump()["events"]
           if e["kind"] == "dequant_gemm"]
    assert len(evs) == 1
    e = evs[0]
    assert e["mode"] == "int8"
    assert e["fp_bytes"] > e["quant_bytes"] > 0
    assert e["fp_bytes"] / e["quant_bytes"] >= 1.8


def _load_trace_summary():
    path = (Path(__file__).resolve().parents[1] / "tools"
            / "trace_summary.py")
    spec = importlib.util.spec_from_file_location("_trace_summary_wq",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_trace_summary_reports_weight_quant_line(tiny):
    ts = _load_trace_summary()
    cfg, model, params = tiny
    obs = Observability(clock=CONST_CLOCK)
    _serve(model, params, _config(weight_quantization="int8"),
           _requests(cfg, n=2), obs=obs)
    report = ts.summarize(obs.dump())
    assert "weight quantization: mode=int8" in report
    assert "x smaller" in report


def test_off_mode_gauges_zero_and_no_event(tiny):
    cfg, model, params = tiny
    obs = Observability(clock=CONST_CLOCK)
    _serve(model, params, _config(), _requests(cfg, n=2), obs=obs)
    expo = obs.metrics.exposition()
    assert 'serving_quantization_mode{kind="kv"} 0' in expo
    assert 'serving_quantization_mode{kind="weight"} 0' in expo
    assert not [e for e in obs.recorder.dump()["events"]
                if e["kind"] == "dequant_gemm"]
