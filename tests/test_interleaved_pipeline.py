"""Interleaved (virtual-pipeline) schedule + microbatch calculator tests
(upstream analog: the interleaved path of
test_pipeline_parallel_fwd_bwd.py and the microbatches calculator
units; SURVEY.md §2.3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.microbatches import (
    ConstantNumMicroBatches,
    RampupBatchsizeNumMicroBatches,
    build_num_microbatches_calculator,
    destroy_microbatch_calculator,
    get_num_microbatches,
    setup_microbatch_calculator,
    update_num_microbatches,
)
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
    spmd_pipeline_interleaved,
)

PP = 4
V = 2   # model chunks per device -> 8 global stages
M = 8   # microbatches (divisible by PP)
MB = 2
H = 8


@pytest.fixture(autouse=True)
def _mp():
    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=1, pipeline_model_parallel_size_=PP
    )
    yield
    parallel_state.destroy_model_parallel()


def _chunk_weights(seed=0):
    """One (H, H) matrix per GLOBAL stage: (V, PP, H, H) so that device r
    chunk c holds global stage c*PP + r."""
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(V, PP, H, H).astype("float32") * 0.3)


def _stage_fn(w, x, mb_idx):
    return jnp.tanh(x @ w)


def _batches(seed=1):
    return jnp.asarray(
        np.random.RandomState(seed).randn(M, MB, H).astype("float32"))


def _sequential_ref(ws_vp, xs):
    """Apply all V*PP global stages in order c*PP + r."""
    h = xs
    for c in range(V):
        for r in range(PP):
            h = jax.vmap(lambda x, w=ws_vp[c, r]: _stage_fn(w, x, 0))(h)
    return h


def test_interleaved_forward_matches_sequential():
    ws = _chunk_weights()
    xs = _batches()

    def f(w_local, xs):
        w = w_local.reshape(V, H, H)  # this device's V chunks
        outs = spmd_pipeline_interleaved(
            _stage_fn, w, xs, num_microbatches=M, num_model_chunks=V)
        pp_rank = jax.lax.axis_index("pipeline")
        return jax.lax.psum(jnp.where(pp_rank == PP - 1, outs, 0.0),
                            "pipeline")

    # shard (V, PP, H, H) over the pipeline axis (dim 1)
    outs = jax.jit(jax.shard_map(
        f, mesh=parallel_state.get_mesh(),
        in_specs=(P(None, "pipeline"), P()), out_specs=P()))(ws, xs)

    ref = _sequential_ref(ws, xs)
    np.testing.assert_allclose(np.asarray(outs), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("remat", [True, False])
def test_interleaved_fwd_bwd_matches_unpipelined(remat):
    ws = _chunk_weights()
    xs = _batches()
    ts = jnp.asarray(
        np.random.RandomState(2).randn(M, MB, H).astype("float32"))

    def f(w_local, xs, ts):
        w = w_local.reshape(V, H, H)

        def loss_fn(out, mb_idx):
            t = jax.lax.dynamic_index_in_dim(ts, mb_idx, keepdims=False)
            return jnp.mean((out - t) ** 2)

        loss, grads = forward_backward_pipelining_with_interleaving(
            _stage_fn, xs, w, num_microbatches=M, loss_fn=loss_fn,
            remat=remat,
        )
        return loss, grads[:, None]

    loss, grads = jax.jit(jax.shard_map(
        f, mesh=parallel_state.get_mesh(),
        in_specs=(P(None, "pipeline"), P(), P()),
        out_specs=(P(), P(None, "pipeline"))))(ws, xs, ts)

    def ref_loss(ws):
        h = _sequential_ref(ws, xs)
        return jnp.mean(jax.vmap(
            lambda o, t: jnp.mean((o - t) ** 2))(h, ts))

    l_ref, g_ref = jax.value_and_grad(ref_loss)(ws)
    np.testing.assert_allclose(float(loss), float(l_ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-5)


def test_interleaved_validates_divisibility():
    ws = _chunk_weights()

    def f(w_local, xs):
        w = w_local.reshape(V, H, H)
        return spmd_pipeline_interleaved(
            _stage_fn, w, xs, num_microbatches=6, num_model_chunks=V)

    with pytest.raises(ValueError):
        jax.jit(jax.shard_map(
            f, mesh=parallel_state.get_mesh(),
            in_specs=(P(None, "pipeline"), P()),
            out_specs=P("pipeline")))(ws, _batches()[:6])


def test_get_forward_backward_func_dispatch():
    assert (get_forward_backward_func()
            is forward_backward_pipelining_without_interleaving)
    assert (get_forward_backward_func(1)
            is forward_backward_pipelining_without_interleaving)
    assert (get_forward_backward_func(2)
            is forward_backward_pipelining_with_interleaving)


# ------------------------------------------------- microbatch calculators

def test_constant_calculator():
    c = ConstantNumMicroBatches(64, 2, 4)
    assert c.get() == 8
    assert c.get_current_global_batch_size() == 64
    c.update(10_000, True)  # no-op
    assert c.get() == 8
    with pytest.raises(ValueError):
        ConstantNumMicroBatches(65, 2, 4)


def test_rampup_calculator():
    # 32 -> 64 in +8 increments over 1000 samples
    c = RampupBatchsizeNumMicroBatches(32, 8, 1000, 64, 2, 4)
    assert c.get_current_global_batch_size() == 32
    assert c.get() == 4
    c.update(500, False)
    assert c.get_current_global_batch_size() == 48
    c.update(2000, False)
    assert c.get_current_global_batch_size() == 64
    assert c.get() == 8


def test_global_calculator_singleton():
    destroy_microbatch_calculator()
    with pytest.raises(RuntimeError):
        get_num_microbatches()
    setup_microbatch_calculator(0, None, 64, 2, 4)
    assert get_num_microbatches() == 8
    update_num_microbatches(100)
    assert get_num_microbatches() == 8
    destroy_microbatch_calculator()


def test_build_calculator_rampup_format():
    with pytest.raises(ValueError):
        build_num_microbatches_calculator(0, [32, 8], 64, 2, 4)
    c = build_num_microbatches_calculator(0, [32, 8, 1000], 64, 2, 4)
    assert isinstance(c, RampupBatchsizeNumMicroBatches)
