"""ZeRO sharded-DP optimizer tests (upstream analog: the contrib
``distributed_fused_adam``/``distributed_fused_lamb`` tests — shrunk
world size, real collectives; SURVEY.md §2.3) on the 8-device CPU mesh.

Core properties, per VERDICT round-1 item 5:
- trajectories match the UNSHARDED FusedAdam/FusedLAMB at dp=8 to fp32
  roundoff (same math, different storage layout);
- per-device optimizer state is N/dp, not N (the ZeRO memory claim);
- skip_if (amp overflow) leaves params, moments, and step untouched.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.contrib.optimizers import (
    DistributedFusedAdam,
    DistributedFusedLAMB,
)
from apex_tpu.optimizers import FusedAdam, FusedLAMB

DP = 8


def _mesh():
    return jax.make_mesh((DP,), ("data",))


def _params(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "w1": jnp.asarray(rng.randn(5, 7).astype("float32")),
        "b1": jnp.asarray(rng.randn(7).astype("float32")),
        "inner": {"w2": jnp.asarray(rng.randn(7, 3).astype("float32"))},
    }


def _per_device_grads():
    """8 distinct grad pytrees stacked on a leading device axis."""
    trees = [_params(seed=10 + i) for i in range(DP)]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


def _mean_grads():
    trees = [_params(seed=10 + i) for i in range(DP)]
    return jax.tree.map(lambda *xs: jnp.stack(xs).mean(0), *trees)


def _run_sharded(opt, params, stacked_grads, steps=3, skip_if=None):
    mesh = _mesh()

    def f(params, grads_stack):
        grads = jax.tree.map(lambda g: g[0], grads_stack)  # this rank's
        state = opt.init(params)
        p = params
        for _ in range(steps):
            p, state = opt.step(grads, state, p, skip_if=skip_if)
        state = state._replace(step=state.step[None])  # rank-0 concat-able
        # stack per-rank copies rather than pmean (the CPU backend's
        # all-reduce is a ulp off even on identical replicas)
        return jax.tree.map(lambda x: x[None], p), state

    p_stack, state = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=(P(), P("data")),
        out_specs=(P("data"), P("data")),
    ))(params, stacked_grads)
    # all ranks must agree exactly after the all_gather
    p_host = jax.tree.map(lambda x: np.asarray(x), p_stack)
    for leaf in jax.tree.leaves(p_host):
        np.testing.assert_array_equal(
            leaf, np.broadcast_to(leaf[0], leaf.shape))
    return jax.tree.map(lambda x: jnp.asarray(x[0]), p_host), state


@pytest.mark.parametrize("dist_opt,ref_opt", [
    (DistributedFusedAdam(lr=1e-2, weight_decay=0.01, group_size=DP),
     FusedAdam(lr=1e-2, weight_decay=0.01)),
    (DistributedFusedAdam(lr=1e-2, weight_decay=0.01, adam_w_mode=False,
                          group_size=DP),
     FusedAdam(lr=1e-2, weight_decay=0.01, adam_w_mode=False)),
    (DistributedFusedLAMB(lr=1e-2, weight_decay=0.01, group_size=DP),
     FusedLAMB(lr=1e-2, weight_decay=0.01)),
    (DistributedFusedLAMB(lr=1e-2, weight_decay=0.0, use_nvlamb=True,
                          group_size=DP),
     FusedLAMB(lr=1e-2, weight_decay=0.0, use_nvlamb=True)),
])
def test_trajectory_matches_unsharded(dist_opt, ref_opt):
    """dp=8 sharded trajectory == unsharded optimizer fed the mean grad."""
    params = _params()
    p_sharded, _ = _run_sharded(dist_opt, params, _per_device_grads())

    mean_g = _mean_grads()
    state = ref_opt.init(params)
    p_ref = params
    for _ in range(3):
        p_ref, state = ref_opt.step(mean_g, state, p_ref)

    for k, a in jax.tree.leaves_with_path(p_sharded):
        b = dict(jax.tree.leaves_with_path(p_ref))[k]
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)


def test_state_is_sharded_n_over_dp():
    """The ZeRO claim: per-device moment/master buffers hold ~N/dp
    elements (padded to dp*128 lanes), not N — held as lane-shaped
    (rows, 128) 2-D buffers (1-D flat state invites the [N,2] tiled-
    layout blowup documented in ops/multi_tensor.py)."""
    params = {"w": jnp.ones((160, 128)), "b": jnp.ones((128,))}  # 20608
    n_total = sum(l.size for l in jax.tree.leaves(params))
    padded = -(-n_total // (DP * 128)) * DP * 128
    rows = padded // DP // 128
    opt = DistributedFusedAdam(group_size=DP)
    mesh = _mesh()

    state = jax.jit(jax.shard_map(
        lambda p: opt.init(p)._replace(step=opt.init(p).step[None]),
        mesh=mesh, in_specs=P(), out_specs=P("data")))(params)
    # per-rank (rows, 128) shards concatenate along axis 0
    assert state.exp_avg.shape == (DP * rows, 128)
    assert state.master.shape == (DP * rows, 128)
    per_device_elems = rows * 128
    assert per_device_elems < n_total / 4  # genuinely sharded


def test_skip_if_freezes_everything():
    params = _params()
    opt = DistributedFusedAdam(lr=1e-2, group_size=DP)
    p1, s1 = _run_sharded(opt, params, _per_device_grads(), steps=2,
                          skip_if=jnp.bool_(True))
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(np.asarray(s1.step).ravel()[0]) == 0


def test_bf16_params_gather_in_model_dtype():
    """Uniform-bf16 models all-gather in bf16 (half the bytes); the
    trajectory still matches the unsharded optimizer stepping bf16 params
    with fp32 masters."""
    params = jax.tree.map(lambda p: p.astype(jnp.bfloat16), _params())
    opt = DistributedFusedAdam(lr=1e-2, group_size=DP)
    assert opt._meta(params).gather_dtype == jnp.bfloat16
    grads = jax.tree.map(lambda p: jnp.stack([p] * DP), params)
    p1, _ = _run_sharded(opt, params, grads, steps=3)

    ref = FusedAdam(lr=1e-2, master_weights=True)
    state = ref.init(params)
    p_ref = params
    for _ in range(3):
        p_ref, state = ref.step(params, state, p_ref)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p_ref)):
        assert a.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=1e-2, atol=1e-3)


def test_predivide_vs_grad_averaging_knobs():
    """predivide_grads (DDP mean) and LAMB's grad_averaging (beta3) are
    independent: turning off grad_averaging must NOT drop the dp mean."""
    opt = DistributedFusedLAMB(lr=1e-2, grad_averaging=False, group_size=DP)
    assert opt.predivide_grads is True
    params = _params()
    p1, _ = _run_sharded(opt, params, _per_device_grads(), steps=2)

    ref = FusedLAMB(lr=1e-2, grad_averaging=False)
    state = ref.init(params)
    p_ref = params
    for _ in range(2):
        p_ref, state = ref.step(_mean_grads(), state, p_ref)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)


def test_unaligned_total_padding():
    """Total param count not divisible by dp: padded tail must stay inert
    and reconstructed params must match exactly."""
    params = {"w": jnp.asarray(np.random.RandomState(0)
                               .randn(3, 5).astype("float32"))}  # 15 % 8 != 0
    opt = DistributedFusedAdam(lr=1e-2, group_size=DP)
    p1, _ = _run_sharded(opt, params, jax.tree.map(
        lambda p: jnp.stack([p] * DP), params), steps=2)

    ref = FusedAdam(lr=1e-2)
    state = ref.init(params)
    p_ref = params
    for _ in range(2):
        p_ref, state = ref.step(params, state, p_ref)
    np.testing.assert_allclose(np.asarray(p1["w"]), np.asarray(p_ref["w"]),
                               rtol=2e-5, atol=2e-6)
