"""Pipeline-parallel tests (upstream analog: tests/L0/run_transformer/
test_pipeline_parallel_fwd_bwd.py, test_p2p_comm.py; SURVEY.md §4):
pipelined loss/grads must match the unpipelined stacked model."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_pipelining_without_interleaving,
    p2p_communication,
    spmd_pipeline,
)

PP = 4
M = 8  # microbatches
MB = 2  # microbatch size
H = 16


@pytest.fixture(autouse=True)
def _mp():
    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=1, pipeline_model_parallel_size_=PP
    )
    yield
    parallel_state.destroy_model_parallel()


def _stage_weights(seed=0):
    """One (H, H) matrix per stage, stacked (PP, H, H)."""
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(PP, H, H).astype("float32") * 0.3)


def _stage_fn(w, x, mb_idx):
    return jnp.tanh(x @ w)


def _batches(seed=1):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(M, MB, H).astype("float32"))


def _targets(seed=2):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(M, MB, H).astype("float32"))


def _run_sharded(f, *args, in_specs, out_specs):
    mesh = parallel_state.get_mesh()
    return jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    )(*args)


def test_pipeline_forward_matches_sequential():
    ws = _stage_weights()
    xs = _batches()

    def f(w_local, xs):
        w = w_local.reshape(H, H)  # local (1, H, H) shard
        outs = spmd_pipeline(_stage_fn, w, xs, num_microbatches=M)
        # only the last stage's outputs are valid; broadcast them
        pp_rank = jax.lax.axis_index("pipeline")
        masked = jnp.where(pp_rank == PP - 1, outs, 0.0)
        return jax.lax.psum(masked, "pipeline")

    outs = _run_sharded(f, ws, xs, in_specs=(P("pipeline"), P()), out_specs=P())

    # sequential reference: x through all 4 stages
    ref = xs
    for s in range(PP):
        ref = jax.vmap(lambda x: _stage_fn(ws[s], x, 0))(ref)
    np.testing.assert_allclose(np.asarray(outs), np.asarray(ref), rtol=1e-4,
                               atol=1e-5)


@pytest.mark.parametrize("remat", [True, False])
def test_pipeline_fwd_bwd_matches_unpipelined(remat):
    ws = _stage_weights()
    xs = _batches()
    ts = _targets()

    def f(w_local, xs, ts):
        w = w_local.reshape(H, H)

        def loss_fn(out, mb_idx):
            t = jax.lax.dynamic_index_in_dim(ts, mb_idx, keepdims=False)
            return jnp.mean((out - t) ** 2)

        loss, grads = forward_backward_pipelining_without_interleaving(
            _stage_fn, xs, w, num_microbatches=M, loss_fn=loss_fn, remat=remat,
        )
        return loss, grads[None]

    loss, grads = _run_sharded(
        f, ws, xs, ts, in_specs=(P("pipeline"), P(), P()),
        out_specs=(P(), P("pipeline")),
    )

    # unpipelined reference
    def ref_loss(ws):
        h = xs
        for s in range(PP):
            h = jax.vmap(lambda x, w=ws[s]: _stage_fn(w, x, 0))(h)
        return jnp.mean(jax.vmap(lambda o, t: jnp.mean((o - t) ** 2))(h, ts))

    l_ref, g_ref = jax.value_and_grad(ref_loss)(ws)
    np.testing.assert_allclose(float(loss), float(l_ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-5)


def test_pipeline_trains():
    """End-to-end: pipelined training reduces the loss."""
    from apex_tpu.optimizers import AdamState, FusedAdam

    ws = _stage_weights()
    xs = _batches()
    ts = _targets()
    opt = FusedAdam(lr=1e-2)

    # per-stage optimizer state, stacked along a leading pp axis
    ost0 = AdamState(
        step=jnp.zeros((), jnp.int32),
        exp_avg={"w": jnp.zeros((PP, H, H), jnp.float32)},
        exp_avg_sq={"w": jnp.zeros((PP, H, H), jnp.float32)},
        master=None,
    )
    ost_spec = AdamState(step=P(), exp_avg={"w": P("pipeline")},
                         exp_avg_sq={"w": P("pipeline")}, master=None)

    def step(w_local, ost, xs, ts):
        w = w_local.reshape(H, H)
        ost = AdamState(
            step=ost.step,
            exp_avg={"w": ost.exp_avg["w"].reshape(H, H)},
            exp_avg_sq={"w": ost.exp_avg_sq["w"].reshape(H, H)},
            master=None,
        )

        def loss_fn(out, mb_idx):
            t = jax.lax.dynamic_index_in_dim(ts, mb_idx, keepdims=False)
            return jnp.mean((out - t) ** 2)

        loss, g = forward_backward_pipelining_without_interleaving(
            _stage_fn, xs, w, num_microbatches=M, loss_fn=loss_fn,
        )
        w2, ost2 = opt.step({"w": g}, ost, {"w": w})
        ost_out = AdamState(
            step=ost2.step,
            exp_avg={"w": ost2.exp_avg["w"][None]},
            exp_avg_sq={"w": ost2.exp_avg_sq["w"][None]},
            master=None,
        )
        return w2["w"][None], ost_out, loss

    mesh = parallel_state.get_mesh()
    stepped = jax.jit(
        jax.shard_map(step, mesh=mesh,
                      in_specs=(P("pipeline"), ost_spec, P(), P()),
                      out_specs=(P("pipeline"), ost_spec, P())))

    w, ost, losses = ws, ost0, []
    for i in range(15):
        w, ost, loss = stepped(w, ost, xs, ts)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses


def test_p2p_send_forward_ring():
    def f(x):
        return p2p_communication.send_forward(x)

    mesh = parallel_state.get_mesh()
    x = jnp.arange(4.0)
    out = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=P("pipeline"), out_specs=P("pipeline"))
    )(x)
    # stage i receives from i-1: ring shift
    np.testing.assert_allclose(np.asarray(out), [3.0, 0.0, 1.0, 2.0])


def test_p2p_send_backward_ring():
    def f(x):
        return p2p_communication.send_backward(x)

    mesh = parallel_state.get_mesh()
    x = jnp.arange(4.0)
    out = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=P("pipeline"), out_specs=P("pipeline"))
    )(x)
    np.testing.assert_allclose(np.asarray(out), [1.0, 2.0, 3.0, 0.0])


def test_shape_changing_pipeline_embed_block_logits():
    """Shape-NEGOTIATING pipeline (reference _communicate handshake):
    token ids -> embeddings -> hidden blocks -> logits travel through
    one fixed carry buffer via pack_carry/unpack_carry; the pipelined
    loss must equal the unpipelined model's loss."""
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        pack_carry,
        unpack_carry,
    )
    from apex_tpu.utils.collectives import mark_varying

    V, S = 11, 4  # vocab, seq
    rng = np.random.RandomState(3)
    embed_t = jnp.asarray(rng.randn(V, H).astype("f4") * 0.5)
    w1 = jnp.asarray(rng.randn(H, H).astype("f4") * 0.3)
    w2 = jnp.asarray(rng.randn(H, H).astype("f4") * 0.3)
    out_w = jnp.asarray(rng.randn(H, V).astype("f4") * 0.3)
    ids = jnp.asarray(rng.randint(0, V, (M, MB, S)))
    targets = jnp.asarray(rng.randint(0, V, (M, MB, S)))

    # carry sized for the largest boundary: logits (MB, S, V)
    struct = jax.ShapeDtypeStruct((MB, S, max(V, H)), jnp.float32)
    params = {"embed": embed_t, "w1": w1, "w2": w2, "out": out_w}

    def stage_fn(p, carry, mb_idx):
        stage = jax.lax.axis_index("pipeline")

        def do_embed(c):
            toks = unpack_carry(c, (MB, S), jnp.int32)
            return pack_carry(p["embed"][toks], struct)

        def do_block(w):
            def f(c):
                h = unpack_carry(c, (MB, S, H), jnp.float32)
                return pack_carry(jnp.tanh(h @ w), struct)
            return f

        def do_logits(c):
            h = unpack_carry(c, (MB, S, H), jnp.float32)
            return pack_carry(h @ p["out"], struct)

        return jax.lax.switch(
            stage, [do_embed, do_block(p["w1"]), do_block(p["w2"]),
                    do_logits], carry)

    def loss_fn(carry, mb_idx, targets):
        logits = unpack_carry(carry, (MB, S, V), jnp.float32)
        t = jax.lax.dynamic_index_in_dim(targets, mb_idx, keepdims=False)
        logp = jax.nn.log_softmax(logits, -1)
        return -jnp.mean(jnp.take_along_axis(logp, t[..., None], -1))

    def f(params, ids, targets):
        packed = jax.vmap(lambda mb: pack_carry(mb, struct))(ids)
        outs = spmd_pipeline(stage_fn, params, packed,
                             num_microbatches=M, carry_struct=struct)
        per_mb = jax.vmap(lambda o, i: loss_fn(o, i, targets))(
            outs, jnp.arange(M))
        local = jnp.mean(per_mb)
        stage = jax.lax.axis_index("pipeline")
        return jax.lax.psum(jnp.where(stage == PP - 1, local, 0.0),
                            "pipeline")

    loss = _run_sharded(f, params, ids, targets,
                        in_specs=(P(), P(), P()), out_specs=P())

    # unpipelined reference
    h = embed_t[ids]
    h = jnp.tanh(h @ w1)
    h = jnp.tanh(h @ w2)
    logits = h @ out_w
    logp = jax.nn.log_softmax(logits, -1)
    ref = -jnp.mean(jnp.take_along_axis(logp, targets[..., None], -1))
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)


def test_carry_struct_validates_packing():
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        pack_carry,
        unpack_carry,
    )

    struct = jax.ShapeDtypeStruct((MB, 8), jnp.float32)
    with pytest.raises(ValueError, match="pre-packed"):
        def g(xs):
            return spmd_pipeline(lambda p, x, i: x, None, xs,
                                 num_microbatches=M, carry_struct=struct)
        _run_sharded(g, _batches(), in_specs=(P(),), out_specs=P("pipeline"))
    with pytest.raises(ValueError, match="exceeds the carry"):
        pack_carry(jnp.zeros((MB, 99)), struct)
    # int round-trip is exact through the float carry
    ids = jnp.asarray(np.random.RandomState(0).randint(-5, 2 ** 30, (4, 3)))
    back = unpack_carry(pack_carry(ids, jax.ShapeDtypeStruct((13,),
                                                             jnp.float32)),
                        (4, 3), ids.dtype)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(ids))


def test_pack_carry_int32_carry_roundtrip():
    """Same-kind (int->int) carries astype; cross-kind bitcasts; 2-byte
    carries with int payloads are rejected (review regression: the
    docstring-recommended i32 carry corrupted ids via a value-cast)."""
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        pack_carry,
        unpack_carry,
    )

    rng = np.random.RandomState(1)
    ids = jnp.asarray(rng.randint(-7, 2 ** 30, (4, 3)))
    i32 = jax.ShapeDtypeStruct((13,), jnp.int32)
    back = unpack_carry(pack_carry(ids, i32), (4, 3), ids.dtype)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(ids))
    # float through an int carry: bitcast round-trip
    xs = jnp.asarray(rng.randn(5).astype("f4"))
    back_f = unpack_carry(pack_carry(xs, i32), (5,), jnp.float32)
    np.testing.assert_array_equal(np.asarray(back_f), np.asarray(xs))
    # 2-byte carry with int payload: loud rejection
    with pytest.raises(ValueError, match="4-byte"):
        pack_carry(ids, jax.ShapeDtypeStruct((13,), jnp.bfloat16))
