"""Pipeline-parallel tests (upstream analog: tests/L0/run_transformer/
test_pipeline_parallel_fwd_bwd.py, test_p2p_comm.py; SURVEY.md §4):
pipelined loss/grads must match the unpipelined stacked model."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_pipelining_without_interleaving,
    p2p_communication,
    spmd_pipeline,
)

PP = 4
M = 8  # microbatches
MB = 2  # microbatch size
H = 16


@pytest.fixture(autouse=True)
def _mp():
    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=1, pipeline_model_parallel_size_=PP
    )
    yield
    parallel_state.destroy_model_parallel()


def _stage_weights(seed=0):
    """One (H, H) matrix per stage, stacked (PP, H, H)."""
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(PP, H, H).astype("float32") * 0.3)


def _stage_fn(w, x, mb_idx):
    return jnp.tanh(x @ w)


def _batches(seed=1):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(M, MB, H).astype("float32"))


def _targets(seed=2):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(M, MB, H).astype("float32"))


def _run_sharded(f, *args, in_specs, out_specs):
    mesh = parallel_state.get_mesh()
    return jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    )(*args)


def test_pipeline_forward_matches_sequential():
    ws = _stage_weights()
    xs = _batches()

    def f(w_local, xs):
        w = w_local.reshape(H, H)  # local (1, H, H) shard
        outs = spmd_pipeline(_stage_fn, w, xs, num_microbatches=M)
        # only the last stage's outputs are valid; broadcast them
        pp_rank = jax.lax.axis_index("pipeline")
        masked = jnp.where(pp_rank == PP - 1, outs, 0.0)
        return jax.lax.psum(masked, "pipeline")

    outs = _run_sharded(f, ws, xs, in_specs=(P("pipeline"), P()), out_specs=P())

    # sequential reference: x through all 4 stages
    ref = xs
    for s in range(PP):
        ref = jax.vmap(lambda x: _stage_fn(ws[s], x, 0))(ref)
    np.testing.assert_allclose(np.asarray(outs), np.asarray(ref), rtol=1e-4,
                               atol=1e-5)


@pytest.mark.parametrize("remat", [True, False])
def test_pipeline_fwd_bwd_matches_unpipelined(remat):
    ws = _stage_weights()
    xs = _batches()
    ts = _targets()

    def f(w_local, xs, ts):
        w = w_local.reshape(H, H)

        def loss_fn(out, mb_idx):
            t = jax.lax.dynamic_index_in_dim(ts, mb_idx, keepdims=False)
            return jnp.mean((out - t) ** 2)

        loss, grads = forward_backward_pipelining_without_interleaving(
            _stage_fn, xs, w, num_microbatches=M, loss_fn=loss_fn, remat=remat,
        )
        return loss, grads[None]

    loss, grads = _run_sharded(
        f, ws, xs, ts, in_specs=(P("pipeline"), P(), P()),
        out_specs=(P(), P("pipeline")),
    )

    # unpipelined reference
    def ref_loss(ws):
        h = xs
        for s in range(PP):
            h = jax.vmap(lambda x, w=ws[s]: _stage_fn(w, x, 0))(h)
        return jnp.mean(jax.vmap(lambda o, t: jnp.mean((o - t) ** 2))(h, ts))

    l_ref, g_ref = jax.value_and_grad(ref_loss)(ws)
    np.testing.assert_allclose(float(loss), float(l_ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-5)


def test_pipeline_trains():
    """End-to-end: pipelined training reduces the loss."""
    from apex_tpu.optimizers import AdamState, FusedAdam

    ws = _stage_weights()
    xs = _batches()
    ts = _targets()
    opt = FusedAdam(lr=1e-2)

    # per-stage optimizer state, stacked along a leading pp axis
    ost0 = AdamState(
        step=jnp.zeros((), jnp.int32),
        exp_avg={"w": jnp.zeros((PP, H, H), jnp.float32)},
        exp_avg_sq={"w": jnp.zeros((PP, H, H), jnp.float32)},
        master=None,
    )
    ost_spec = AdamState(step=P(), exp_avg={"w": P("pipeline")},
                         exp_avg_sq={"w": P("pipeline")}, master=None)

    def step(w_local, ost, xs, ts):
        w = w_local.reshape(H, H)
        ost = AdamState(
            step=ost.step,
            exp_avg={"w": ost.exp_avg["w"].reshape(H, H)},
            exp_avg_sq={"w": ost.exp_avg_sq["w"].reshape(H, H)},
            master=None,
        )

        def loss_fn(out, mb_idx):
            t = jax.lax.dynamic_index_in_dim(ts, mb_idx, keepdims=False)
            return jnp.mean((out - t) ** 2)

        loss, g = forward_backward_pipelining_without_interleaving(
            _stage_fn, xs, w, num_microbatches=M, loss_fn=loss_fn,
        )
        w2, ost2 = opt.step({"w": g}, ost, {"w": w})
        ost_out = AdamState(
            step=ost2.step,
            exp_avg={"w": ost2.exp_avg["w"][None]},
            exp_avg_sq={"w": ost2.exp_avg_sq["w"][None]},
            master=None,
        )
        return w2["w"][None], ost_out, loss

    mesh = parallel_state.get_mesh()
    stepped = jax.jit(
        jax.shard_map(step, mesh=mesh,
                      in_specs=(P("pipeline"), ost_spec, P(), P()),
                      out_specs=(P("pipeline"), ost_spec, P())))

    w, ost, losses = ws, ost0, []
    for i in range(15):
        w, ost, loss = stepped(w, ost, xs, ts)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses


def test_p2p_send_forward_ring():
    def f(x):
        return p2p_communication.send_forward(x)

    mesh = parallel_state.get_mesh()
    x = jnp.arange(4.0)
    out = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=P("pipeline"), out_specs=P("pipeline"))
    )(x)
    # stage i receives from i-1: ring shift
    np.testing.assert_allclose(np.asarray(out), [3.0, 0.0, 1.0, 2.0])


def test_p2p_send_backward_ring():
    def f(x):
        return p2p_communication.send_backward(x)

    mesh = parallel_state.get_mesh()
    x = jnp.arange(4.0)
    out = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=P("pipeline"), out_specs=P("pipeline"))
    )(x)
    np.testing.assert_allclose(np.asarray(out), [1.0, 2.0, 3.0, 0.0])
