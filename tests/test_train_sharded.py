"""apex_tpu.train, sharded: the 3D-parallel single-dispatch step.

The GSPMD ``build_train_step(mesh=...)`` promotion (ISSUE 20): scanned
accumulation + amp overflow skip + ZeRO flat-shard optimizer update +
tensor-parallel activations, compiled into ONE donated dispatch on the
serving mesh. The certification ladder mirrors PR 4's fused-vs-loop
contract: a (1, 1) mesh is BIT-identical to the meshless step across
the amp x optimizer x accum matrix; real mesh shapes hold the
drift-bounded tier (the test_train_step.py SPMD concession) with the
compile count pinned at one; and the per-mesh collective contract is
certified from AOT-lowered HLO, never from wall-clock.
"""

import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import flax.linen as nn

import apex_tpu.amp as amp
from apex_tpu.contrib.optimizers import (
    DistributedFusedAdam,
    DistributedFusedLAMB,
)
from apex_tpu.models.gpt import GPTConfig, GPTLMHeadModel, lm_loss
from apex_tpu.optimizers import FusedAdam, FusedLAMB
from apex_tpu.serving.mesh import (
    build_mesh,
    train_expected_collectives,
)
from apex_tpu.train import (
    NonFiniteLossError,
    WatchdogConfig,
    build_train_step,
)
from apex_tpu.utils.checkpoint import (
    load_train_state,
    save_train_state,
    state_mesh_shape,
)
from apex_tpu.utils.faults import FaultPlan, FaultSpec
from apex_tpu.utils.hlo_audit import collective_stats


# ---------------------------------------------------------------------------
# fixtures: a tiny GPT (the TP-decomposed tree) and a small dense net
# ---------------------------------------------------------------------------


ACCUM, B, S = 2, 4, 16


@pytest.fixture(scope="module")
def gpt_setup():
    cfg = GPTConfig.tiny(dropout=0.0, remat=False)
    model = GPTLMHeadModel(cfg)
    tokens = np.asarray(jax.random.randint(
        jax.random.PRNGKey(1), (ACCUM, B, S), 0, cfg.vocab_size))
    params = jax.device_get(
        model.init(jax.random.PRNGKey(0), jnp.asarray(tokens[0]))["params"])

    def loss_fn(p, mb):
        return lm_loss(model.apply({"params": p}, mb), mb)

    return cfg, loss_fn, params, tokens


def _gpt_run(gpt_setup, optimizer, mesh_shape, steps=3, amp_handle=None):
    cfg, loss_fn, params, tokens = gpt_setup
    kw = dict(amp=amp_handle, accum_steps=ACCUM)
    if mesh_shape is not None:
        kw.update(mesh=build_mesh(mesh_shape), num_heads=cfg.num_heads)
    ts = build_train_step(loss_fn, optimizer, **kw)
    state = ts.init(jax.tree.map(jnp.asarray, params))
    losses = []
    for _ in range(steps):
        state, metrics = ts.step(state, jnp.asarray(tokens))
        losses.append(float(jax.device_get(metrics["loss"])))
    return ts, state, losses


class _Net(nn.Module):
    """Dense net WITH a norm layer so the O2 arm exercises the mixed
    fp32/bf16 tree (the test_train_step.py Net, shrunk)."""

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(32, param_dtype=jnp.float32)(x)
        x = nn.LayerNorm(param_dtype=jnp.float32)(x)
        return nn.Dense(4, param_dtype=jnp.float32)(nn.relu(x))


@pytest.fixture(scope="module")
def net_setup():
    model = _Net()
    rng = np.random.RandomState(0)
    xs = jnp.asarray(rng.randn(4, 8, 16).astype("f4"))
    ys = jnp.asarray(rng.randint(0, 4, (4, 8)))
    params = jax.device_get(
        model.init(jax.random.PRNGKey(1), xs[0])["params"])

    def loss_fn(p, mb):
        x, y = mb
        logits = model.apply({"params": p}, x).astype(jnp.float32)
        lp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(lp, y[:, None], 1))

    return loss_fn, params, (xs, ys)


def _trees_bit_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _trees_certified(a, b):
    """The sharded drift-bounded tier (test_train_step.py
    ``_assert_certified_equal`` rationale: XLA:CPU rounds fp32 SPMD
    arithmetic differently per partitioning; a composition bug is off
    by 1e-1..65536x, not 1e-3). The absolute floor is 1e-5, not 1e-6:
    near-zero-initialized GPT biases sit at ~1e-6 after a few Adam
    steps, where cross-partitioning fp32 roundoff (~5e-6 absolute) is
    the whole signal."""
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-3, atol=1e-5)


# ---------------------------------------------------------------------------
# (1, 1) bit-identity matrix: amp x optimizer x accum
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("accum", [1, 4])
@pytest.mark.parametrize("opt_cls", [FusedAdam, DistributedFusedAdam])
@pytest.mark.parametrize("opt_level", ["O1", "O2"])
def test_mesh11_bit_identity_matrix(net_setup, opt_level, opt_cls, accum):
    """A (1, 1) mesh must be a spelling of the meshless step, not a
    different program: params, optimizer state, and scaler state stay
    BIT-identical through the full amp composition, and each side
    compiles exactly once."""
    loss_fn, params, (xs, ys) = net_setup
    xs, ys = xs[:accum], ys[:accum]

    def make(mesh_shape):
        opt = (opt_cls(lr=1e-2, flat_mode="global")
               if opt_cls is DistributedFusedAdam else opt_cls(lr=1e-2))
        p, opt, handle = amp.initialize(
            jax.tree.map(jnp.asarray, params), opt,
            opt_level=opt_level, verbosity=0)
        kw = dict(amp=handle, accum_steps=accum)
        if mesh_shape is not None:
            kw["mesh"] = build_mesh(mesh_shape)
        ts = build_train_step(loss_fn, opt, **kw)
        return ts, ts.init(p)

    ts0, s0 = make(None)
    ts1, s1 = make((1, 1))
    for _ in range(3):
        s0, m0 = ts0.step(s0, (xs, ys))
        s1, m1 = ts1.step(s1, (xs, ys))
    _trees_bit_equal(s0.params, s1.params)
    _trees_bit_equal(s0.opt_state, s1.opt_state)
    _trees_bit_equal(s0.scaler_state, s1.scaler_state)
    assert float(jax.device_get(m0["loss"])) == \
        float(jax.device_get(m1["loss"]))
    assert ts1._jitted._cache_size() == 1


# ---------------------------------------------------------------------------
# sharded certs: real mesh shapes vs the meshless step
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def gpt_meshless_ref(gpt_setup):
    """Meshless 3-step trajectories, one per optimizer family."""
    out = {}
    for name, opt in [("adam", FusedAdam(lr=1e-3)),
                      ("zero", DistributedFusedAdam(lr=1e-3,
                                                    flat_mode="global"))]:
        _, state, losses = _gpt_run(gpt_setup, opt, None)
        out[name] = (jax.device_get(state.params), losses)
    return out


@pytest.mark.parametrize("mesh_shape", [(2, 1), (1, 2), (2, 2)])
@pytest.mark.parametrize("opt_name", ["adam", "zero"])
def test_sharded_cert_and_collective_contract(gpt_setup, gpt_meshless_ref,
                                              opt_name, mesh_shape):
    """Every real mesh shape: drift-bounded agreement with the meshless
    trajectory, ONE compile for 3 dispatched steps, and the AOT audit
    pins the per-mesh collective contract (ZeRO round trip for the
    flat optimizer, >= 2*num_layers all-reduces on the TP leg, no
    all-to-all of real data) plus a positive donation-alias count."""
    cfg, _, _, tokens = gpt_setup
    opt = (FusedAdam(lr=1e-3) if opt_name == "adam"
           else DistributedFusedAdam(lr=1e-3, flat_mode="global"))
    ts, state, losses = _gpt_run(gpt_setup, opt, mesh_shape)
    ref_params, ref_losses = gpt_meshless_ref[opt_name]
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4)
    _trees_certified(state.params, ref_params)
    assert ts._jitted._cache_size() == 1
    audit = ts.audit_collectives(state, jnp.asarray(tokens))
    assert audit["alias"]["pairs"] >= audit["sharded_leaves"] > 0
    table = {k: v["ops"] for k, v in audit["collectives"].items()
             if k not in ("total", "degenerate")}
    assert table["all-to-all"] == 0 and table["collective-permute"] == 0
    if mesh_shape[1] > 1:
        # the TP leg: one all-reduce per block matmul pair, forward and
        # backward — the >= 2*num_layers floor of the contract
        assert table["all-reduce"] >= 2 * cfg.num_layers
    if mesh_shape[0] > 1 and opt_name == "zero":
        # the ZeRO leg, either HLO spelling
        assert (table["reduce-scatter"] >= 1
                or table["all-reduce"] >= 1)
        assert table["all-gather"] >= 1
    assert audit["contract"] == train_expected_collectives(
        mesh_shape, num_layers=cfg.num_layers, zero=(opt_name == "zero"))


def test_mesh11_audit_is_collective_free(gpt_setup):
    """The (1, 1) contract is exact: zero collective ops in the whole
    compiled global step."""
    ts, state, _ = _gpt_run(gpt_setup, FusedAdam(lr=1e-3), (1, 1),
                            steps=1)
    cfg, _, _, tokens = gpt_setup
    audit = ts.audit_collectives(state, jnp.asarray(tokens))
    assert audit["contract"] == {"exact_total_ops": 0}
    assert audit["collectives"]["total"]["ops"] == 0


def test_audit_requires_gspmd_path(net_setup):
    loss_fn, params, _ = net_setup
    ts = build_train_step(loss_fn, FusedAdam(lr=1e-2), accum_steps=1)
    state = ts.init(jax.tree.map(jnp.asarray, params))
    with pytest.raises(ValueError, match="mesh"):
        ts.audit_collectives(state, None)


# ---------------------------------------------------------------------------
# satellite 1: mesh-geometry validation with named-knob errors
# ---------------------------------------------------------------------------


def test_geometry_model_axis_must_divide_heads(net_setup, gpt_setup):
    cfg, loss_fn, _, _ = gpt_setup
    with pytest.raises(ValueError, match="num_heads"):
        build_train_step(loss_fn, FusedAdam(lr=1e-3), accum_steps=ACCUM,
                         mesh=build_mesh((1, 8)), num_heads=cfg.num_heads)


def test_geometry_axis_names_must_match_serving_mesh(net_setup):
    loss_fn, _, _ = net_setup
    bad = jax.make_mesh((2, 1), ("dp", "tp"))
    with pytest.raises(ValueError, match="batch.*model|model.*batch"):
        build_train_step(loss_fn, FusedAdam(lr=1e-2), accum_steps=1,
                         mesh=bad)


def test_geometry_batch_axis_must_divide_batch_dim(net_setup):
    """B=8 microbatches cannot shard over an 8-way batch axis when a
    leaf's batch dim is smaller — the error names the offending leaf
    dim and the knob."""
    loss_fn, params, (xs, ys) = net_setup
    ts = build_train_step(loss_fn, FusedAdam(lr=1e-2), accum_steps=4,
                          mesh=build_mesh((8, 1)))
    state = ts.init(jax.tree.map(jnp.asarray, params))
    bad = (xs[:, :6], ys[:, :6])  # batch dim 6, batch axis 8
    with pytest.raises(ValueError, match="batch"):
        ts.step(state, bad)


def test_geometry_zero_group_size_must_match_batch_axis(net_setup):
    loss_fn, _, _ = net_setup
    opt = DistributedFusedAdam(lr=1e-2, flat_mode="global", group_size=3)
    with pytest.raises(ValueError, match="group_size"):
        build_train_step(loss_fn, opt, accum_steps=1,
                         mesh=build_mesh((2, 1)))


# ---------------------------------------------------------------------------
# satellite 2: flat-buffer padding counted once and exposed
# ---------------------------------------------------------------------------


def test_flat_pad_stats_surface():
    opt = DistributedFusedAdam(lr=1e-2, flat_mode="global")
    with pytest.raises(ValueError, match="stats"):
        opt.stats()
    params = {"w": jnp.ones((5, 7)), "b": jnp.ones((3,))}
    opt.init(params)
    st = opt.stats()
    assert st["flat_total_elems"] == 5 * 7 + 3
    assert st["flat_padded_elems"] == \
        st["flat_total_elems"] + st["flat_pad_elems"]
    assert st["flat_padded_elems"] % 128 == 0
    assert st["flat_world"] == 1
    assert st["flat_shard_elems"] * st["flat_world"] == \
        st["flat_padded_elems"]
    assert st["opt_state_bytes_per_shard"] == st["flat_shard_elems"] * 12
    # counted once: the meta is cached per (world, tree) key
    assert opt.stats() == st


def test_flat_pad_stats_sharded(gpt_setup, net_setup):
    loss_fn, params, _ = net_setup
    opt = DistributedFusedAdam(lr=1e-2, flat_mode="global")
    ts = build_train_step(loss_fn, opt, accum_steps=1,
                          mesh=build_mesh((2, 1)))
    ts.init(jax.tree.map(jnp.asarray, params))
    st = ts._core.optimizer.stats()
    assert st["flat_world"] == 2
    assert st["flat_shard_elems"] * 2 == st["flat_padded_elems"]


# ---------------------------------------------------------------------------
# checkpoint/resume under sharding
# ---------------------------------------------------------------------------


def test_sharded_checkpoint_resume_bit_identical(gpt_setup, tmp_path):
    """Save at step 2 on a (2, 1) mesh, resume onto an EQUAL mesh:
    steps 3-4 of the resumed run are bit-identical to the
    uninterrupted one, and the resumed step re-dispatches the compiled
    program (no retrace). A (1, 2) template is REFUSED by the mesh
    fingerprint; a meshless template still loads (the payload is
    host-replicated, topology-free)."""
    cfg, loss_fn, params, tokens = gpt_setup

    def make(shape):
        kw = dict(accum_steps=ACCUM)
        if shape is not None:
            kw.update(mesh=build_mesh(shape), num_heads=cfg.num_heads)
        ts = build_train_step(loss_fn, FusedAdam(lr=1e-3), **kw)
        return ts, ts.init(jax.tree.map(jnp.asarray, params))

    ts, state = make((2, 1))
    assert state_mesh_shape(state) == [["batch", 2], ["model", 1]]
    for _ in range(2):
        state, _ = ts.step(state, jnp.asarray(tokens))
    save_train_state(str(tmp_path), state)
    ref = state
    for _ in range(2):
        ref, _ = ts.step(ref, jnp.asarray(tokens))

    ts2, tmpl = make((2, 1))
    resumed, step = load_train_state(str(tmp_path), tmpl)
    assert step == 2
    for _ in range(2):
        resumed, _ = ts2.step(resumed, jnp.asarray(tokens))
    _trees_bit_equal(ref.params, resumed.params)
    _trees_bit_equal(ref.opt_state, resumed.opt_state)
    assert ts2._jitted._cache_size() == 1

    ts3, tmpl3 = make((1, 2))
    with pytest.raises(ValueError, match="mesh"):
        load_train_state(str(tmp_path), tmpl3)

    _, tmpl4 = make(None)
    st4, step4 = load_train_state(str(tmp_path), tmpl4)
    assert step4 == 2 and state_mesh_shape(st4) is None


# ---------------------------------------------------------------------------
# watchdog rescale under sharding
# ---------------------------------------------------------------------------


def test_watchdog_rescale_survives_sharding(net_setup):
    """The watchdog's host-side loss-scale halving must re-commit the
    replacement scalar onto the mesh — an uncommitted leaf would make
    the next dispatch retrace (and a donated retrace recompiles the
    whole global step)."""
    from apex_tpu.amp.scaler import LossScaler

    loss_fn, params, (xs, ys) = net_setup
    ts = build_train_step(loss_fn, FusedAdam(lr=1e-2),
                          amp=LossScaler(), accum_steps=1,
                          mesh=build_mesh((2, 1)))
    loop = ts.loop(
        ts.init(jax.tree.map(jnp.asarray, params)),
        faults=FaultPlan([FaultSpec(site="train_step", kind="nan",
                                    every=1)]),
        watchdog=WatchdogConfig(skip_steps=1, rescale_steps=2,
                                min_scale=1.0))
    scale0 = float(jax.device_get(loop.state.scaler_state.loss_scale))
    batches = [(xs[:1], ys[:1])] * 8
    with pytest.raises(NonFiniteLossError):
        loop.run(batches)
    s = loop.stats()
    assert s["watchdog_rescales"] == 2
    scale1 = float(jax.device_get(loop.state.scaler_state.loss_scale))
    assert scale1 == scale0 / 4
    # the rebuilt scalar landed back on the mesh, and the program
    # never retraced through the rescues
    sharding = loop.state.scaler_state.loss_scale.sharding
    assert getattr(sharding, "mesh", None) is not None
    assert ts._jitted._cache_size() == 1


# ---------------------------------------------------------------------------
# ZeRO LAMB on the global path
# ---------------------------------------------------------------------------


def test_lamb_global_smoke(net_setup):
    """DistributedFusedLAMB's flat_mode="global" world-of-1 must track
    the per-leaf FusedLAMB trajectory (same math, flat storage)."""
    loss_fn, params, (xs, ys) = net_setup
    runs = {}
    for name, opt in [("ref", FusedLAMB(lr=1e-2)),
                      ("flat", DistributedFusedLAMB(lr=1e-2,
                                                    flat_mode="global"))]:
        ts = build_train_step(loss_fn, opt, accum_steps=2)
        state = ts.init(jax.tree.map(jnp.asarray, params))
        for _ in range(3):
            state, _ = ts.step(state, (xs[:2], ys[:2]))
        runs[name] = jax.device_get(state.params)
    _trees_certified(runs["flat"], runs["ref"])


# ---------------------------------------------------------------------------
# hlo_audit: degenerate-collective classification (unit)
# ---------------------------------------------------------------------------


_SYNTH_HLO = """
  %broadcast.1 = f32[1,32,32]{2,1,0} broadcast(f32[] %constant.9), dimensions={}
  %all-to-all.1 = (f32[1,32,32]{2,1,0}, f32[1,32,32]{2,1,0}) all-to-all(f32[1,32,32]{2,1,0} %broadcast.1, f32[1,32,32]{2,1,0} %broadcast.1), channel_id=7
  %all-reduce.1 = f32[64,64]{1,0} all-reduce(f32[64,64]{1,0} %add.5), channel_id=8
"""


def test_collective_stats_degenerate_classification():
    """An all-to-all whose every operand is a scalar broadcast (the
    CSE-merged constant artifact) is excluded only under
    ``exclude_degenerate=True`` — and a real-data collective never
    is."""
    raw = collective_stats(_SYNTH_HLO)
    assert raw["all-to-all"]["ops"] == 1
    assert raw["all-reduce"]["ops"] == 1
    assert "degenerate" not in raw
    strict = collective_stats(_SYNTH_HLO, exclude_degenerate=True)
    assert strict["all-to-all"]["ops"] == 0
    assert strict["degenerate"]["ops"] == 1
    assert strict["all-reduce"]["ops"] == 1
    assert strict["total"]["ops"] == 1


def test_train_expected_collectives_table():
    assert train_expected_collectives((1, 1)) == {"exact_total_ops": 0}
    tp = train_expected_collectives((1, 2), num_layers=2)
    assert tp["min_ops"]["all-reduce"] == 4
    assert "all-to-all" in tp["forbidden"]
    z = train_expected_collectives((2, 2), num_layers=2, zero=True)
    assert z["min_ops"]["reduce-scatter"] == 1
    assert z["alt_min_ops"]["all-gather"] >= 1
    assert "all-to-all" in z["forbidden"]
