"""Out-of-process replica certification (tier-1, CPU): the ISSUE 16
layer (docs/fleet.md, "Process replicas").

The wire protocol's failure taxonomy (round trip, clean close,
truncation, rot, bad JSON, oversize refusal at both ends, timeout —
every damaged frame an ``IntegrityError``, never a silent mis-parse);
the seeded ``"wire"`` fault site (truncating/rotting chaos hook,
construction-time kind validation, plan serialization and the
wire/child split); the serialization layer (EngineConfig, Request,
clock specs, the numpy array codec); the :class:`ProcessReplica`
surface against a REAL child process — status mirroring, engine-error
mapping, the retry + at-most-once dedupe loop under injected frame
damage, the params-checksum boot handshake; the 1-process-replica
fleet bit-identity cert (outputs, statuses, full stats; greedy +
sampled, speculation on/off); and the SIGKILL chaos cert — a real
``kill -9`` of a child mid-burst with zero lost accepted requests,
exactly-once terminals, and respawn into a fresh OS process."""

import json
import os
import signal
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import GPTConfig, GPTLMHeadModel
from apex_tpu.serving import (
    EngineConfig,
    FleetConfig,
    FleetRouter,
    ProcessReplica,
    ReplicaUnavailableError,
    Request,
    SamplingParams,
    TenantQuota,
)
from apex_tpu.serving import wire
from apex_tpu.serving.process_replica import (
    build_model_from_spec,
    clock_from_spec,
    engine_config_from_record,
    engine_config_record,
    gpt_model_spec,
    params_checksum,
    request_from_record,
    request_record,
)
from apex_tpu.utils.faults import (
    FaultPlan,
    FaultSpec,
    plan_from_record,
    plan_record,
    split_plan,
    validate_wire_specs,
    wire_chaos,
)
from apex_tpu.utils.integrity import IntegrityError

ENGINE_KW = dict(max_batch=2, block_size=4, num_blocks=32,
                 max_prefill_len=8, max_seq_len=32, seed=7,
                 enable_prefix_caching=True)


@pytest.fixture(scope="module")
def tiny_gpt():
    cfg = GPTConfig.tiny(dropout=0.0, remat=False)
    model = GPTLMHeadModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    return cfg, model, params


@pytest.fixture()
def pipe_pair():
    r, w = os.pipe()
    yield r, w
    for fd in (r, w):
        try:
            os.close(fd)
        except OSError:
            pass


def _reqs(n=5, sampled=True, prompt_len=6, new=5, seed=3, uid="r"):
    rng = np.random.RandomState(seed)
    out = []
    for k in range(n):
        prompt = list(rng.randint(1, 50, prompt_len))
        samp = (SamplingParams(temperature=1.0, top_k=10)
                if sampled and k % 2 == 0 else SamplingParams())
        out.append(Request(f"{uid}{k}", prompt, max_new_tokens=new,
                           sampling=samp))
    return out


# ---------------------------------------------------------------------------
# the frame protocol
# ---------------------------------------------------------------------------


def test_frame_round_trip(pipe_pair):
    r, w = pipe_pair
    rec = {"type": "call", "id": 3, "method": "step",
           "args": [[1, 2], {"k": 0.5, "s": "x"}], "flag": True}
    wire.write_frame(w, dict(rec))
    got = wire.read_frame(r)
    got.pop("checksum")
    assert got == rec


def test_frame_clean_eof_is_wire_closed(pipe_pair):
    r, w = pipe_pair
    os.close(w)
    with pytest.raises(wire.WireClosedError):
        wire.read_frame(r)


def test_frame_truncated_header_and_body(pipe_pair):
    r, w = pipe_pair
    frame = wire.encode_frame({"type": "x"})
    # a few header bytes, then EOF: torn, not clean-closed
    os.write(w, frame[:3])
    os.close(w)
    with pytest.raises(IntegrityError, match="truncated header"):
        wire.read_frame(r)
    r2, w2 = os.pipe()
    try:
        os.write(w2, frame[:-4])     # full header, partial body
        os.close(w2)
        with pytest.raises(IntegrityError, match="truncated body"):
            wire.read_frame(r2)
    finally:
        os.close(r2)


def test_frame_rotted_byte_raises_integrity(pipe_pair):
    r, w = pipe_pair
    frame = bytearray(wire.encode_frame({"type": "resp", "value": 7}))
    # flip one byte inside a JSON number: still valid JSON, but the
    # embedded checksum no longer matches
    idx = frame.index(b'"value":7') + len(b'"value":')
    frame[idx] = ord("9")
    os.write(w, bytes(frame))
    with pytest.raises(IntegrityError):
        wire.read_frame(r)


def test_frame_garbage_body_raises_integrity(pipe_pair):
    r, w = pipe_pair
    body = b"\xff\xfenot json"
    os.write(w, wire._HEADER.pack(len(body)) + body)
    with pytest.raises(IntegrityError, match="torn frame"):
        wire.read_frame(r)
    # a valid-JSON non-object body is refused too
    body = json.dumps([1, 2, 3]).encode()
    os.write(w, wire._HEADER.pack(len(body)) + body)
    with pytest.raises(IntegrityError, match="record object"):
        wire.read_frame(r)


def test_frame_oversize_refused_both_ends(pipe_pair):
    r, w = pipe_pair
    with pytest.raises(IntegrityError, match="oversize"):
        wire.encode_frame({"blob": "x" * 256}, max_bytes=64)
    # a corrupt length prefix is refused before any body allocation
    os.write(w, wire._HEADER.pack(wire.MAX_FRAME_BYTES + 1))
    with pytest.raises(IntegrityError, match="oversize frame refused"):
        wire.read_frame(r)


def test_frame_timeout(pipe_pair):
    r, w = pipe_pair
    with pytest.raises(wire.WireTimeoutError):
        wire.read_frame(r, timeout_s=0.05)
    # ... including stalling mid-frame
    frame = wire.encode_frame({"type": "x"})
    os.write(w, frame[: wire.HEADER_BYTES + 2])
    with pytest.raises(wire.WireTimeoutError):
        wire.read_frame(r, timeout_s=0.05)


def test_frame_write_survives_pipe_buffer(pipe_pair):
    # a frame larger than the pipe buffer must still round-trip (the
    # writer loops over partial os.write results)
    r, w = pipe_pair
    rec = {"type": "bulk", "blob": "a" * (1 << 20)}
    err = []

    def reader():
        try:
            got = wire.read_frame(r, timeout_s=30.0)
            assert got["blob"] == rec["blob"]
        except Exception as e:  # pragma: no cover - surfaced below
            err.append(e)

    t = threading.Thread(target=reader)
    t.start()
    wire.write_frame(w, rec)
    t.join(timeout=30.0)
    assert not err and not t.is_alive()


def test_arrays_codec_round_trip():
    payload = {
        "k": np.arange(24, dtype=np.float32).reshape(2, 3, 4),
        "nested": {"v": np.array([1, -2, 3], dtype=np.int64),
                   "scalar": 7, "s": "txt"},
        "list": [np.zeros((2, 2), dtype=np.float16), None, True],
    }
    enc = wire.encode_arrays(payload)
    json.dumps(enc)    # must be JSON-able as-is
    dec = wire.decode_arrays(enc)
    np.testing.assert_array_equal(dec["k"], payload["k"])
    assert dec["k"].dtype == np.float32
    np.testing.assert_array_equal(dec["nested"]["v"],
                                  payload["nested"]["v"])
    assert dec["list"][0].dtype == np.float16
    assert dec["nested"]["scalar"] == 7 and dec["list"][1:] == [None, True]
    # the input tree was not mutated
    assert isinstance(payload["k"], np.ndarray)


# ---------------------------------------------------------------------------
# the "wire" fault site
# ---------------------------------------------------------------------------


def test_wire_chaos_transient_truncates(pipe_pair):
    r, w = pipe_pair
    plan = FaultPlan([FaultSpec(site="wire", kind="transient", at=(1,))])
    hook = wire_chaos(plan)
    wire.write_frame(w, {"type": "a", "n": 1})
    wire.write_frame(w, {"type": "b", "n": 2})
    assert wire.read_frame(r, chaos=hook)["type"] == "a"   # call 0: clean
    with pytest.raises(IntegrityError):                    # call 1: torn
        wire.read_frame(r, chaos=hook)
    assert plan.counts() == {"wire": {"transient": 1}}


def test_wire_chaos_corrupt_rots_checksum(pipe_pair):
    r, w = pipe_pair
    plan = FaultPlan([FaultSpec(site="wire", kind="corrupt", at=(0,))],
                     seed=11)
    hook = wire_chaos(plan)
    wire.write_frame(w, {"type": "resp", "id": 5, "result": 42})
    with pytest.raises(IntegrityError):
        wire.read_frame(r, chaos=hook)
    # deterministic: the same plan rots the same frame the same way
    plan2 = FaultPlan([FaultSpec(site="wire", kind="corrupt", at=(0,))],
                      seed=11)
    body = wire.encode_frame(
        {"type": "resp", "id": 5, "result": 42})[wire.HEADER_BYTES:]
    assert wire_chaos(plan2)(body) == wire_chaos(FaultPlan(
        [FaultSpec(site="wire", kind="corrupt", at=(0,))], seed=11))(body)


def test_validate_wire_specs():
    validate_wire_specs([FaultSpec(site="wire", kind="corrupt", at=(0,)),
                         FaultSpec(site="wire", kind="transient", at=(1,)),
                         FaultSpec(site="decode", kind="crash", at=(0,))])
    for kind in ("crash", "nan"):
        with pytest.raises(ValueError, match="not valid at site"):
            validate_wire_specs([FaultSpec(site="wire", kind=kind,
                                           at=(0,))])


def test_plan_record_round_trip_and_split():
    plan = FaultPlan([
        FaultSpec(site="wire", kind="corrupt", at=(2,), max_fires=1),
        FaultSpec(site="decode", kind="transient", every=3),
        FaultSpec(site="wire", kind="transient", prob=0.5),
    ], seed=9)
    clone = plan_from_record(json.loads(json.dumps(plan_record(plan))))
    assert clone.seed == plan.seed and clone.specs == plan.specs
    here, there = split_plan(plan, "wire")
    assert [s.site for s in here.specs] == ["wire", "wire"]
    assert [s.site for s in there.specs] == ["decode"]
    assert here.seed == there.seed == 9
    assert split_plan(None, "wire") == (None, None)
    only_wire, none = split_plan(FaultPlan(
        [FaultSpec(site="wire", kind="corrupt", at=(0,))]), "wire")
    assert none is None and len(only_wire.specs) == 1


# ---------------------------------------------------------------------------
# serialization: configs, requests, clocks
# ---------------------------------------------------------------------------


def test_engine_config_record_round_trip():
    cfg = EngineConfig(**ENGINE_KW, kv_dtype="float32",
                       mesh_shape=(1, 1),
                       tenant_quotas={"a": TenantQuota(max_waiting=3)},
                       tenant_weights={"a": 2.0})
    rec = json.loads(json.dumps(engine_config_record(cfg)))
    clone = engine_config_from_record(rec)
    assert clone.max_batch == cfg.max_batch
    assert clone.mesh_shape == (1, 1)
    assert str(jnp.dtype(clone.kv_dtype)) == "float32"
    assert clone.tenant_quotas["a"].max_waiting == 3
    assert clone.tenant_weights == {"a": 2.0}
    # the identity that matters: the restore fingerprints match
    rec2 = engine_config_record(clone)
    assert rec2 == engine_config_record(engine_config_from_record(rec2))


def test_request_record_round_trip():
    req = Request("u1", [3, 1, 4], max_new_tokens=6,
                  sampling=SamplingParams(temperature=0.7, top_k=5,
                                          top_p=0.9),
                  eos_token_id=2, deadline_s=1.5, priority=1,
                  tenant="acme")
    clone = request_from_record(json.loads(json.dumps(
        request_record(req))))
    assert (clone.uid, clone.prompt, clone.max_new_tokens) == \
        ("u1", [3, 1, 4], 6)
    assert (clone.sampling.temperature, clone.sampling.top_k,
            clone.sampling.top_p) == (0.7, 5, 0.9)
    assert (clone.eos_token_id, clone.deadline_s, clone.priority,
            clone.tenant) == (2, 1.5, 1, "acme")


def test_clock_from_spec():
    assert clock_from_spec(None) is None
    assert clock_from_spec({"kind": "monotonic"}) is None
    frozen = clock_from_spec({"kind": "constant", "t": 2.5})
    assert frozen() == 2.5 and frozen() == 2.5
    with pytest.raises(ValueError, match="clock spec"):
        clock_from_spec({"kind": "wall"})


def test_model_spec_rebuilds_identical_weights(tiny_gpt):
    cfg, _, params = tiny_gpt
    spec = json.loads(json.dumps(gpt_model_spec(cfg)))
    _, rebuilt = build_model_from_spec(spec)
    assert params_checksum(rebuilt) == params_checksum(params)
    with pytest.raises(ValueError, match="model family"):
        build_model_from_spec({"family": "bert", "config": {}})


# ---------------------------------------------------------------------------
# process-mode construction validation (no child is ever spawned)
# ---------------------------------------------------------------------------


def test_fleet_process_mode_validation(tiny_gpt):
    cfg, model, params = tiny_gpt
    ecfg = EngineConfig(**ENGINE_KW)
    with pytest.raises(ValueError, match="replica_mode"):
        FleetConfig(replica_mode="thread")
    with pytest.raises(ValueError, match="rpc_timeout_s"):
        FleetConfig(rpc_timeout_s=0.0)
    with pytest.raises(ValueError, match="rpc_retries"):
        FleetConfig(rpc_retries=-1)
    with pytest.raises(ValueError, match="model_spec"):
        FleetRouter(model, params, ecfg,
                    FleetConfig(num_replicas=1, replica_mode="process"))
    with pytest.raises(ValueError, match="child_clock"):
        FleetRouter(model, params, ecfg,
                    FleetConfig(num_replicas=1, replica_mode="process"),
                    model_spec=gpt_model_spec(cfg), clock=lambda: 0.0)
    with pytest.raises(ValueError, match="child_clock"):
        FleetRouter(model, params, ecfg, FleetConfig(num_replicas=1),
                    child_clock={"kind": "constant", "t": 0.0})
    with pytest.raises(ValueError, match="wire"):
        FleetRouter(model, params, ecfg, FleetConfig(num_replicas=1),
                    faults=[FaultPlan([FaultSpec(site="wire",
                                                 kind="corrupt",
                                                 at=(0,))])])


# ---------------------------------------------------------------------------
# the ProcessReplica surface (one real child)
# ---------------------------------------------------------------------------


def test_process_replica_surface_and_error_mapping(tiny_gpt):
    cfg, _, params = tiny_gpt
    spec = gpt_model_spec(cfg)
    rep = ProcessReplica(EngineConfig(**ENGINE_KW), spec,
                         expect_params_checksum=params_checksum(params),
                         clock_spec={"kind": "constant", "t": 0.0})
    try:
        assert rep.mode == "process" and rep.alive
        assert rep.child_pid > 0
        assert not rep.has_work
        assert rep.queue_depth == 0 and rep.active_slot_count == 0
        req = Request("p0", [5, 6, 7], max_new_tokens=3,
                      sampling=SamplingParams())
        assert rep.add_request(req) == 0
        assert req.status is None            # door passed, mirrored
        assert rep.queue_depth == 1 and rep.has_work
        # an engine-level refusal maps back to the REAL local type
        with pytest.raises(ValueError, match="max_seq_len"):
            rep.add_request(Request("bad", [1] * 40, max_new_tokens=2,
                                    sampling=SamplingParams()))
        # per-tenant accessors mirror the in-process narrow surface
        assert rep.tenant_depth("nosuch") == 0
        load = rep.load()
        assert set(load) >= {"queue_depth", "active_slots",
                             "blocks_allocatable"}
        assert rep.block_weight > 0
        assert rep.probe_prefix([]) == 0
        n = 0
        while rep.has_work and n < 60:
            rep.step()
            n += 1
        res = rep.pop_results()
        assert res["p0"].status == "finished"
        assert len(res["p0"].tokens) == 3
        assert req.status == "finished"      # terminal status mirrored
        assert rep.abort("p0") is False      # already terminal
        snap = rep.checkpoint()
        assert rep.last_checkpoint is snap and "checksum" in snap
        stats = rep.stats()
        json.dumps(stats)                    # JSON-normalized by wire
        assert stats["num_ticks"] > 0
        # an unknown RPC method is a loud ValueError, not a hang
        with pytest.raises(ValueError, match="unknown RPC method"):
            rep._call("frobnicate")
    finally:
        rep.close()
    assert not rep.alive
    with pytest.raises(ReplicaUnavailableError):
        rep.step()
    rep.kill()          # idempotent on a closed handle


def test_process_replica_retry_and_at_most_once(tiny_gpt):
    """Injected frame damage on RPC responses: the parent resends the
    SAME id, the worker answers duplicates from its response cache
    without re-executing — so a retried add_request never
    double-enqueues (the at-most-once cert)."""
    cfg, _, params = tiny_gpt
    retries = []
    # response frames: call 0 rotted (stale checksum), call 2 torn
    plan = FaultPlan([FaultSpec(site="wire", kind="corrupt", at=(0,)),
                      FaultSpec(site="wire", kind="transient", at=(2,))],
                     seed=5)
    rep = ProcessReplica(EngineConfig(**ENGINE_KW), gpt_model_spec(cfg),
                         expect_params_checksum=params_checksum(params),
                         clock_spec={"kind": "constant", "t": 0.0},
                         faults=plan, rpc_retries=2,
                         on_retry=lambda: retries.append(1))
    try:
        req = Request("q0", [9, 8, 7], max_new_tokens=3,
                      sampling=SamplingParams())
        assert rep.add_request(req) == 0     # call 0 rotted -> retried
        assert len(retries) == 1
        assert rep.queue_depth == 1          # call 2 torn -> retried;
        assert len(retries) == 2             # and NOT double-enqueued
        out = {}
        n = 0
        while rep.has_work and n < 60:
            rep.step()
            out.update(rep.pop_results())
            n += 1
        out.update(rep.pop_results())
        assert out["q0"].status == "finished"
        # split_plan kept the wire rules parent-side; its audit log
        # shows exactly the two injected hits
        assert rep.wire_faults.counts()["wire"] == {"corrupt": 1,
                                                    "transient": 1}
    finally:
        rep.close()


def test_child_refuses_params_checksum_mismatch(tiny_gpt):
    """The boot handshake: a model spec that does not reproduce the
    parent's weights is refused at hello, never served."""
    cfg, _, _ = tiny_gpt
    with pytest.raises(IntegrityError, match="checksum"):
        ProcessReplica(EngineConfig(**ENGINE_KW), gpt_model_spec(cfg),
                       expect_params_checksum="0" * 64)


# ---------------------------------------------------------------------------
# the 1-process-replica fleet bit-identity cert
# ---------------------------------------------------------------------------


def _normalized_stats(fleet):
    st = fleet.stats()
    for row in st["replicas"].values():
        # the per-replica "mode" is the ONE documented difference
        # between the arms (docs/fleet.md, "Process replicas")
        row.pop("mode")
    return json.loads(json.dumps(st, sort_keys=True, default=str))


@pytest.mark.parametrize("spec_tokens", [0, 3])
def test_single_process_replica_fleet_bit_identical(tiny_gpt,
                                                    spec_tokens):
    cfg, model, params = tiny_gpt
    ecfg = EngineConfig(**ENGINE_KW, spec_tokens=spec_tokens)
    outs = {}
    for mode in ("in_process", "process"):
        kw = {}
        if mode == "process":
            kw = dict(model_spec=gpt_model_spec(cfg),
                      child_clock={"kind": "constant", "t": 0.0})
        fleet = FleetRouter(model, params, ecfg,
                            FleetConfig(num_replicas=1,
                                        replica_mode=mode),
                            clock=lambda: 0.0, **kw)
        try:
            for req in _reqs(n=5, sampled=True):
                fleet.add_request(req)
            res = fleet.run(return_status=True)
            outs[mode] = (
                {u: (tuple(r.tokens), r.status) for u, r in res.items()},
                _normalized_stats(fleet))
        finally:
            fleet.close()
    assert outs["process"][0] == outs["in_process"][0]
    assert outs["process"][1] == outs["in_process"][1]


# ---------------------------------------------------------------------------
# the SIGKILL chaos cert: kill -9 a real child mid-burst
# ---------------------------------------------------------------------------


def test_fleet_survives_real_sigkill(tiny_gpt):
    cfg, model, params = tiny_gpt
    ecfg = EngineConfig(**ENGINE_KW, snapshot_interval_ticks=2)
    fleet = FleetRouter(
        model, params, ecfg,
        FleetConfig(num_replicas=2, replica_mode="process",
                    respawn=True, rpc_timeout_s=60.0),
        model_spec=gpt_model_spec(cfg))
    try:
        reqs = _reqs(n=6, sampled=True, uid="k")
        for req in reqs:
            fleet.add_request(req)
        for _ in range(3):
            fleet.step()
        victim = fleet.replicas[0].engine
        pid0 = victim.child_pid
        os.kill(pid0, signal.SIGKILL)        # a REAL kill -9
        res = fleet.run(return_status=True)
        # zero lost accepted requests, exactly-once terminals
        assert sorted(res) == sorted(r.uid for r in reqs)
        assert all(r.status == "finished" for r in res.values())
        st = fleet.stats()
        assert st["num_lost_requests"] == 0
        assert st["num_replicas_down"] == 1
        assert st["num_failovers"] == 1
        assert st["num_respawns"] == 1
        # the slot respawned into a FRESH OS process
        fresh = fleet.replicas[0].engine
        assert fresh is not victim and fresh is not None
        assert fresh.child_pid != pid0 and fresh.alive
        # the corpse really is gone (waitpid would have reaped it;
        # poll() on the handle did)
        assert not victim.alive
    finally:
        fleet.close()
    # close() disposed every child: none of the handles poll alive
    assert all(rep.engine is None or not rep.engine.alive
               for rep in fleet.replicas)


def test_router_kill_replica_is_a_real_sigkill(tiny_gpt):
    """kill_replica in process mode delivers an actual SIGKILL (the
    chaos hook stops simulating) and recovery still runs from the
    parent-cached checkpoint alone."""
    cfg, model, params = tiny_gpt
    ecfg = EngineConfig(**ENGINE_KW, snapshot_interval_ticks=2)
    fleet = FleetRouter(
        model, params, ecfg,
        FleetConfig(num_replicas=2, replica_mode="process",
                    rpc_timeout_s=60.0),
        model_spec=gpt_model_spec(cfg))
    try:
        reqs = _reqs(n=4, sampled=False, uid="s")
        for req in reqs:
            fleet.add_request(req)
        for _ in range(2):
            fleet.step()
        victim = fleet.replicas[0].engine
        pid0 = victim.child_pid
        fleet.kill_replica(0)
        # the child process is DEAD (SIGKILL delivered, corpse reaped)
        assert not victim.alive
        with pytest.raises(OSError):
            os.kill(pid0, 0)        # no such process (reaped by wait)
        assert fleet.replicas[0].engine is None
        res = fleet.run(return_status=True)
        assert sorted(res) == sorted(r.uid for r in reqs)
        assert fleet.stats()["num_lost_requests"] == 0
    finally:
        fleet.close()
