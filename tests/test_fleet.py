"""Fleet-serving certification (tier-1, CPU): the ISSUE 13 layer
(docs/fleet.md).

The router's determinism bar: a 1-replica fleet is bit-identical to
the bare engine (outputs, statuses, schedule counters; greedy +
sampled, speculation on/off); migration mid-decode resumes
bit-identically; failover from the periodic lightweight checkpoint
(``snapshot_interval_ticks``) loses zero accepted requests and
re-derives post-checkpoint tokens exactly. Plus: the lightweight
checkpoint restore cert (the PR 6 cert extended), the spill-store
export/import transport (re-admit token-identical to recompute),
affinity/load routing, fleet-wide quotas, the router-level poison
quarantine, the recorder/trace_summary surface, and a fuzz
interleaving of add/abort/kill/migrate asserting every accepted uid
reaches exactly one terminal status fleet-wide."""

import importlib.util
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import GPTConfig, GPTLMHeadModel
from apex_tpu.observability import Observability
from apex_tpu.serving import (
    EngineConfig,
    FleetConfig,
    FleetFailedError,
    FleetRouter,
    HostSpillStore,
    InferenceEngine,
    Request,
    SamplingParams,
    TenantQuota,
    TenantThrottledError,
)
from apex_tpu.utils.faults import FaultPlan, FaultSpec


@pytest.fixture(scope="module")
def tiny_gpt():
    cfg = GPTConfig.tiny(dropout=0.0, remat=False)
    model = GPTLMHeadModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    return model, params


ENGINE_KW = dict(max_batch=2, block_size=4, num_blocks=32,
                 max_prefill_len=8, max_seq_len=32, seed=7,
                 enable_prefix_caching=True)


def _engine(tiny_gpt, clock=None, **overrides):
    model, params = tiny_gpt
    kw = dict(ENGINE_KW)
    kw.update(overrides)
    return InferenceEngine(model, params, EngineConfig(**kw),
                           clock=clock)


def _fleet(tiny_gpt, n=2, fleet_kw=None, clock=None, faults=None,
           obs=None, **overrides):
    model, params = tiny_gpt
    kw = dict(ENGINE_KW)
    kw.update(overrides)
    return FleetRouter(model, params, EngineConfig(**kw),
                       FleetConfig(num_replicas=n, **(fleet_kw or {})),
                       clock=clock, faults=faults, obs=obs)


def _reqs(n=5, sampled=True, prompt_len=6, new=5, seed=3, uid="r"):
    rng = np.random.RandomState(seed)
    out = []
    for k in range(n):
        prompt = list(rng.randint(1, 50, prompt_len))
        samp = (SamplingParams(temperature=1.0, top_k=10)
                if sampled and k % 2 == 0 else SamplingParams())
        out.append(Request(f"{uid}{k}", prompt, max_new_tokens=new,
                           sampling=samp))
    return out


def _resdict(res):
    return {u: (tuple(r.tokens), r.status) for u, r in res.items()}


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------


def test_fleet_config_validation():
    with pytest.raises(ValueError, match="num_replicas"):
        FleetConfig(num_replicas=0)
    with pytest.raises(ValueError, match="affinity_weight"):
        FleetConfig(affinity_weight=-1.0)
    with pytest.raises(ValueError, match="health_patience"):
        FleetConfig(health_patience=0)
    with pytest.raises(ValueError, match="max_request_failovers"):
        FleetConfig(max_request_failovers=0)
    with pytest.raises(ValueError, match="tenant_rate_tau_s"):
        FleetConfig(tenant_rate_tau_s=0.0)
    with pytest.raises(ValueError, match="TenantQuota"):
        FleetConfig(tenant_quotas={"a": 3})
    with pytest.raises(ValueError, match="tokens_per_s"):
        FleetConfig(tenant_quotas={"a": TenantQuota(tokens_per_s=-1)})


def test_engine_config_snapshot_interval_validation():
    with pytest.raises(ValueError, match="snapshot_interval_ticks"):
        EngineConfig(**ENGINE_KW, snapshot_interval_ticks=0)


def test_per_replica_lists_must_match(tiny_gpt):
    model, params = tiny_gpt
    with pytest.raises(ValueError, match="faults"):
        FleetRouter(model, params, EngineConfig(**ENGINE_KW),
                    FleetConfig(num_replicas=2), faults=[None])


# ---------------------------------------------------------------------------
# the 1-replica identity cert
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("spec", [0, 3])
def test_single_replica_fleet_bit_identical(tiny_gpt, spec):
    """1-replica fleet == bare engine bit-for-bit: outputs, terminal
    statuses, AND the full stats dict (schedule counters included) —
    greedy + sampled lanes, speculation on and off, under a constant
    clock so every time-derived stat compares exactly."""
    kw = dict(spec_tokens=spec, snapshot_interval_ticks=2)
    bare = _engine(tiny_gpt, clock=lambda: 0.0, **kw)
    for r in _reqs():
        bare.add_request(r)
    bare_res = bare.run(return_status=True)
    bare_stats = bare.stats()

    fleet = _fleet(tiny_gpt, n=1, clock=lambda: 0.0, **kw)
    for r in _reqs():
        fleet.add_request(r)
    fleet_res = fleet.run(return_status=True)
    assert _resdict(fleet_res) == _resdict(bare_res)
    assert fleet.replicas[0].engine.stats() == bare_stats
    assert fleet.stats()["num_lost_requests"] == 0


# ---------------------------------------------------------------------------
# the lightweight checkpoint (satellite: snapshot_interval_ticks)
# ---------------------------------------------------------------------------


def test_checkpoint_restore_rederives_inflight_tokens(tiny_gpt):
    """The PR 6 restore cert extended to checkpoint(): a LIGHTWEIGHT
    checkpoint taken WITHOUT draining the in-flight decode restores
    into a run bit-identical to the uninterrupted one — the tokens the
    undrained dispatch held are re-derived deterministically."""
    ref = _engine(tiny_gpt)
    for r in _reqs(n=3, new=8):
        ref.add_request(r)
    expect = ref.run(return_status=True)

    eng = _engine(tiny_gpt)
    for r in _reqs(n=3, new=8):
        eng.add_request(r)
    for _ in range(3):
        eng.step()
    assert eng._pending is not None, "no in-flight dispatch to strand"
    snap = eng.checkpoint()
    assert snap["lightweight"] is True
    # the checkpoint did NOT drain: the dispatch is still in flight
    assert eng._pending is not None
    assert eng.stats()["num_checkpoints"] == 1
    assert eng.stats()["num_snapshots"] == 0

    fresh = _engine(tiny_gpt)
    fresh.restore(snap)
    resumed = fresh.run(return_status=True)
    # pre-checkpoint terminal results (if any) rode the snapshot's
    # finished section; combined, the two runs equal the reference
    combined = dict(expect)
    assert {u: (r.tokens, r.status) for u, r in resumed.items()} == \
        {u: (combined[u].tokens, combined[u].status) for u in resumed}
    assert set(resumed) | set(snap["finished"]) == set(expect)


def test_snapshot_interval_auto_checkpoints(tiny_gpt):
    eng = _engine(tiny_gpt, snapshot_interval_ticks=2)
    assert eng.last_checkpoint is None
    for r in _reqs(n=2):
        eng.add_request(r)
    eng.run()
    stats = eng.stats()
    assert stats["num_checkpoints"] >= 2
    assert eng.last_checkpoint is not None
    # the final checkpoint is restorable (an empty engine picture by
    # then — but the format round-trips)
    fresh = _engine(tiny_gpt, snapshot_interval_ticks=2)
    fresh.restore(eng.last_checkpoint)


def test_interval_knob_out_of_restore_fingerprint(tiny_gpt):
    eng = _engine(tiny_gpt, snapshot_interval_ticks=2)
    for r in _reqs(n=1):
        eng.add_request(r)
    snap = eng.snapshot()
    fresh = _engine(tiny_gpt)   # no interval — still restorable
    fresh.restore(snap)
    assert fresh.run() is not None


# ---------------------------------------------------------------------------
# export / import (the migration records)
# ---------------------------------------------------------------------------


def test_export_import_resumes_bit_identical(tiny_gpt):
    """Engine-level drain-and-migrate: export a mid-decode request
    from A, import into B (same config/seed) — B's continuation is
    bit-identical to the never-migrated run, greedy AND sampled."""
    ref = _engine(tiny_gpt)
    for r in _reqs(n=2, new=8):
        ref.add_request(r)
    expect = ref.run()

    a = _engine(tiny_gpt)
    for r in _reqs(n=2, new=8):
        a.add_request(r)
    for _ in range(4):
        a.step()
    records = a.export_requests(["r0"])
    assert [r["uid"] for r in records] == ["r0"]
    assert a.stats()["num_migrated_out"] == 1
    a.check_allocator_integrity()

    b = _engine(tiny_gpt)
    b.import_requests(records)
    assert b.stats()["num_migrated_in"] == 1
    out_b = b.run()
    out_a = a.run()
    assert out_b["r0"] == expect["r0"]
    assert out_a["r1"] == expect["r1"]


def test_export_all_releases_everything(tiny_gpt):
    eng = _engine(tiny_gpt)
    for r in _reqs(n=4):
        eng.add_request(r)
    for _ in range(2):
        eng.step()
    records = eng.export_requests()
    assert len(records) == 4
    assert not eng.has_work
    eng.check_allocator_integrity()
    assert eng._live_uids == set()
    # exported requests got NO terminal status (they are alive
    # elsewhere): nothing to drain
    assert eng.run() == {}


def test_import_rejects_duplicate_uid(tiny_gpt):
    eng = _engine(tiny_gpt)
    req = _reqs(n=1)[0]
    eng.add_request(req)
    with pytest.raises(ValueError, match="already waiting"):
        eng.import_requests([{
            "uid": req.uid, "prompt": [1, 2], "max_new_tokens": 2,
            "sampling": {"temperature": 0.0, "top_k": 0, "top_p": 1.0},
        }])


def test_import_preserves_deadline_budget(tiny_gpt):
    t = [0.0]
    a = _engine(tiny_gpt, clock=lambda: t[0])
    a.add_request(Request("d0", [1, 2, 3, 4], max_new_tokens=4,
                          deadline_s=10.0))
    t[0] = 4.0
    rec = a.export_requests(["d0"])[0]
    assert rec["deadline_remaining_s"] == pytest.approx(6.0)
    t2 = [100.0]
    b = _engine(tiny_gpt, clock=lambda: t2[0])
    b.import_requests([rec])
    assert b._deadline["d0"] == pytest.approx(106.0)


# ---------------------------------------------------------------------------
# spill-store transport (satellite: export_entry / import_entry)
# ---------------------------------------------------------------------------


def test_spill_export_import_readmits_token_identical(tiny_gpt):
    """The cross-replica KV transport: blocks spilled on A, exported,
    imported into B's store — B serves the prompt token-identical to
    a plain recompute engine, with a nonzero spill hit rate."""
    spill_kw = dict(spill_max_bytes=1 << 20)
    prompt = list(np.random.RandomState(11).randint(1, 50, 12))

    def serve(eng, uid):
        eng.add_request(Request(uid, list(prompt), max_new_tokens=4))
        return eng.run()[uid]

    a = _engine(tiny_gpt, **spill_kw)
    expect = serve(a, "warm")
    # flush the device prefix cache: every registered block spills
    a.allocator.flush_evictable()
    assert len(a.spill) > 0
    hashes = a._seq_hashes(prompt)
    payloads = {h: a.spill.export_entry(h) for h in hashes
                if h in a.spill}
    assert payloads
    # export is a PEEK: A's store still holds (and can re-admit) them
    assert len(a.spill) == len(payloads)

    b = _engine(tiny_gpt, **spill_kw)
    assert b.import_prefix_payloads(payloads) == len(payloads)
    got = serve(b, "migrated")
    assert got == expect
    assert b.stats()["spill_hits"] > 0
    b.check_allocator_integrity()

    plain = _engine(tiny_gpt)
    assert serve(plain, "recompute") == expect


def test_spill_import_entry_validates_payload():
    store = HostSpillStore(1 << 16)
    with pytest.raises(ValueError, match="missing"):
        store.import_entry("h", {"k": np.zeros(4)})
    payload = {"k": np.zeros(4, np.float32), "v": np.ones(4, np.float32)}
    assert store.import_entry("h", payload) is True
    out = store.export_entry("h")
    np.testing.assert_array_equal(out["v"], payload["v"])
    out["v"][0] = 7.0   # deep copy: the store's entry is untouched
    np.testing.assert_array_equal(store.export_entry("h")["v"],
                                  payload["v"])
    assert store.export_entry("missing") is None


# ---------------------------------------------------------------------------
# fleet routing
# ---------------------------------------------------------------------------


def test_affinity_routing_prefers_warm_replica(tiny_gpt):
    fleet = _fleet(tiny_gpt, n=2)
    prompt = list(np.random.RandomState(5).randint(1, 50, 8))
    fleet.add_request(Request("warm", list(prompt), max_new_tokens=2))
    fleet.run()
    # replica 0 (ties break low) now caches the prompt's blocks; a
    # same-prefix request must land there, a distinct one elsewhere
    fleet.add_request(Request("hit", list(prompt), max_new_tokens=2))
    assert fleet.owners()["hit"] == 0
    other = list(np.random.RandomState(6).randint(50, 99, 8))
    fleet.add_request(Request("cold", other, max_new_tokens=2))
    assert fleet.owners()["cold"] == 1
    fleet.run()
    assert fleet.stats()["num_affinity_hits"] >= 1


def test_fleet_uid_uniqueness_and_abort(tiny_gpt):
    fleet = _fleet(tiny_gpt, n=2)
    req = _reqs(n=1)[0]
    fleet.add_request(req)
    with pytest.raises(ValueError, match="already live"):
        fleet.add_request(Request(req.uid, [1, 2], max_new_tokens=2))
    assert fleet.abort(req.uid) is True
    assert fleet.abort("ghost") is False
    res = fleet.run(return_status=True)
    assert res[req.uid].status == "cancelled"
    assert fleet.stats()["num_lost_requests"] == 0


def test_fleet_door_quota_aggregates_across_replicas(tiny_gpt):
    fleet = _fleet(tiny_gpt, n=2, fleet_kw=dict(
        tenant_quotas={"t": TenantQuota(max_waiting=2)}))
    reqs = _reqs(n=3, uid="q", sampled=False)
    for r in reqs[:2]:
        fleet.add_request(Request(r.uid, list(r.prompt),
                                  max_new_tokens=2, tenant="t"))
    # per-replica depth is 1 each — only the FLEET aggregate trips
    with pytest.raises(TenantThrottledError, match="fleet"):
        fleet.add_request(Request("q2", list(reqs[2].prompt),
                                  max_new_tokens=2, tenant="t"))
    assert fleet.try_add(Request("q3", [1, 2, 3],
                                 max_new_tokens=2, tenant="t")) is False
    res = fleet.run(return_status=True)
    assert res["q2"].status == "throttled"
    stats = fleet.stats()
    assert stats["num_throttled"] == 2
    assert stats["tenants"]["t"]["statuses"]["router_throttled"] == 2


# ---------------------------------------------------------------------------
# failover
# ---------------------------------------------------------------------------


def test_crash_fault_failover_zero_loss(tiny_gpt):
    """An injected FaultPlan crash escapes the replica's step() — the
    router declares it dead and re-homes everything; every accepted
    uid reaches exactly one terminal status."""
    faults = [FaultPlan([FaultSpec(site="decode", kind="crash", at=(2,))],
                        seed=1),
              None]
    fleet = _fleet(tiny_gpt, n=2, faults=faults,
                   snapshot_interval_ticks=2)
    for r in _reqs(n=4, new=6):
        fleet.add_request(r)
    res = fleet.run(return_status=True)
    stats = fleet.stats()
    assert set(res) == {f"r{k}" for k in range(4)}
    assert stats["num_failovers"] == 1
    assert stats["num_replicas_down"] == 1
    assert stats["replicas_alive"] == 1
    assert stats["num_lost_requests"] == 0
    assert all(r.status in ("finished", "failed") for r in res.values())
    assert sum(r.status == "finished" for r in res.values()) >= 3


def test_kill_replica_rederives_from_checkpoint(tiny_gpt):
    """Hard kill (engine discarded unread): recovery from the last
    periodic checkpoint alone, and the re-homed requests' token
    streams equal the no-kill fleet run bit-for-bit (arrival identity
    rides the checkpoint records; equal seeds across the fleet)."""
    def build():
        fleet = _fleet(tiny_gpt, n=2, snapshot_interval_ticks=2)
        for r in _reqs(n=4, new=6):
            fleet.add_request(r)
        return fleet

    ref = build()
    expect = ref.run(return_status=True)

    fleet = build()
    for _ in range(3):
        fleet.step()
    killed = fleet.owners()["r0"]
    fleet.kill_replica(killed)
    assert fleet.replicas[killed].engine is None
    res = fleet.run(return_status=True)
    assert _resdict(res) == _resdict(expect)
    assert fleet.stats()["num_lost_requests"] == 0
    assert fleet.stats()["num_failovers"] == 1


def test_failover_without_checkpoint_reinjects_fresh(tiny_gpt):
    """No snapshot_interval_ticks and a hard kill: last_checkpoint is
    None, so everything re-injects fresh from the router's Request
    copies — still zero loss (fresh arrivals, so sampled draws may
    differ; nothing was delivered, so nothing diverges)."""
    fleet = _fleet(tiny_gpt, n=2)
    for r in _reqs(n=4, sampled=False):
        fleet.add_request(r)
    for _ in range(2):
        fleet.step()
    fleet.kill_replica(0)
    res = fleet.run(return_status=True)
    stats = fleet.stats()
    assert set(res) == {f"r{k}" for k in range(4)}
    assert stats["num_lost_requests"] == 0
    assert stats["num_reinjected_requests"] >= 1


def test_stalled_replica_fails_over_after_patience(tiny_gpt):
    fleet = _fleet(tiny_gpt, n=2, fleet_kw=dict(health_patience=2),
                   snapshot_interval_ticks=1)
    for r in _reqs(n=2, sampled=False):
        fleet.add_request(r)
    fleet.step()
    # wedge replica 0: has work, but step() reports no progress
    victim = fleet.replicas[0].engine
    if not victim.has_work:
        pytest.skip("routing sent nothing to replica 0")
    victim.step = lambda: False
    res = fleet.run(return_status=True)
    stats = fleet.stats()
    assert stats["num_replicas_down"] == 1
    assert fleet.replicas[0].alive is False
    assert "stall" in fleet.replicas[0].error
    assert set(res) == {"r0", "r1"}
    assert stats["num_lost_requests"] == 0


def test_poison_request_router_quarantine(tiny_gpt):
    """A request that keeps killing replicas terminal-fails at the
    router (max_request_failovers) instead of cascading forever: every
    replica — respawns included, which reuse the slot's fault plan —
    crashes EVERY decode dispatch, so only the quarantine can end the
    run. The fleet survives and the verdict is exactly-once."""
    model, params = tiny_gpt
    plans = [FaultPlan([FaultSpec(site="decode", kind="crash",
                                  every=1)], seed=s) for s in (2, 3)]
    fleet = FleetRouter(
        model, params, EngineConfig(**ENGINE_KW),
        FleetConfig(num_replicas=2, respawn=True,
                    max_request_failovers=2),
        faults=plans)
    fleet.add_request(_reqs(n=1, sampled=False)[0])
    res = fleet.run(return_status=True)
    stats = fleet.stats()
    assert res["r0"].status == "failed"
    assert stats["num_router_failed"] == 1
    assert stats["num_replicas_down"] == 3   # max_request_failovers + 1
    assert stats["num_respawns"] == 3
    assert stats["num_lost_requests"] == 0
    assert stats["replicas_alive"] == 2      # the fleet itself survived


def test_all_replicas_dead_raises_fleet_failed(tiny_gpt):
    faults = [FaultPlan([FaultSpec(site="decode", kind="crash",
                                   at=(0,))], seed=3)]
    fleet = _fleet(tiny_gpt, n=1, faults=faults,
                   fleet_kw=dict(max_request_failovers=5))
    fleet.add_request(_reqs(n=1, sampled=False)[0])
    with pytest.raises(FleetFailedError):
        fleet.run()


# ---------------------------------------------------------------------------
# migration (fleet-level)
# ---------------------------------------------------------------------------


def test_migration_mid_decode_bit_identical(tiny_gpt):
    """drain-and-migrate mid-decode: the migrated fleet run equals the
    unmigrated fleet run bit-for-bit (greedy + sampled lanes)."""
    def build():
        fleet = _fleet(tiny_gpt, n=2)
        for r in _reqs(n=3, new=8):
            fleet.add_request(r)
        return fleet

    ref = build()
    expect = ref.run(return_status=True)

    fleet = build()
    for _ in range(3):
        fleet.step()
    src = fleet.owners().get("r0")
    if src is None:
        pytest.skip("r0 already finished before migration")
    moved = fleet.migrate(["r0"], src)
    assert moved == 1
    assert fleet.owners()["r0"] != src
    res = fleet.run(return_status=True)
    assert _resdict(res) == _resdict(expect)
    stats = fleet.stats()
    assert stats["num_migrations"] == 1
    assert stats["num_migrated_requests"] == 1
    assert stats["num_lost_requests"] == 0


def test_drain_replica_retires_cleanly(tiny_gpt):
    fleet = _fleet(tiny_gpt, n=2)
    for r in _reqs(n=4, sampled=False):
        fleet.add_request(r)
    fleet.step()
    moved = fleet.drain_replica(0, retire=True)
    assert fleet.replicas[0].alive is False
    assert fleet.replicas[0].error == "retired"
    res = fleet.run(return_status=True)
    assert set(res) == {f"r{k}" for k in range(4)}
    stats = fleet.stats()
    assert stats["num_failovers"] == 0      # clean: no failover path
    assert stats["num_migrated_requests"] == moved
    assert stats["num_lost_requests"] == 0


def test_retire_delivers_results_finished_by_the_export_drain(tiny_gpt):
    """Regression: export_requests drains the in-flight decode, which
    can FINISH a lane (budget hit inside the synced dispatch) — a
    retire must collect that verdict before leaving the per-tick
    drain loop, or the result would be stranded on the corpse."""
    fleet = _fleet(tiny_gpt, n=2)
    fleet.add_request(Request("tiny", [1, 2, 3, 4, 5],
                              max_new_tokens=2))
    src = fleet.owners()["tiny"]
    eng = fleet.replicas[src].engine
    # step the ENGINE directly so the finishing drain happens inside
    # drain_replica's export, not a router tick
    while eng._pending is None and eng.has_work:
        eng.step()
    assert eng._pending is not None
    moved = fleet.drain_replica(src, retire=True)
    assert moved == 0          # the export's drain finished it first
    res = fleet.run(return_status=True)
    assert res["tiny"].status == "finished"
    assert len(res["tiny"].tokens) == 2
    assert fleet.stats()["num_lost_requests"] == 0


def test_migration_ships_spill_payloads(tiny_gpt):
    """With spill tiers on both ends, migration seeds the target's
    store with the prompt's KV payloads — the target re-admits by
    upload (spill_hits > 0) instead of recomputing."""
    fleet = _fleet(tiny_gpt, n=2, spill_max_bytes=1 << 20)
    prompt = list(np.random.RandomState(9).randint(1, 50, 12))
    fleet.add_request(Request("m0", list(prompt), max_new_tokens=6))
    src = fleet.owners()["m0"]
    # let it prefill + decode a little so blocks are registered
    for _ in range(4):
        fleet.step()
    if fleet.owners().get("m0") is None:
        pytest.skip("request finished before migration")
    dst = 1 - src
    fleet.migrate(["m0"], src, dst=dst)
    fleet.run()
    assert fleet.replicas[dst].engine.stats()["spill_hits"] > 0


# ---------------------------------------------------------------------------
# observability surface
# ---------------------------------------------------------------------------


def test_router_recorder_events_and_trace_summary(tiny_gpt, tmp_path):
    obs = Observability(trace=False, metrics=False)
    fleet = _fleet(tiny_gpt, n=2, snapshot_interval_ticks=2, obs=obs)
    for r in _reqs(n=4, new=8, sampled=False):
        fleet.add_request(r)
    for _ in range(2):
        fleet.step()
    # everything onto replica 1 (a migrate event), then kill it (a
    # replica_down + failover re-homing onto replica 0)
    moved = fleet.migrate(None, 0, dst=1)
    assert moved > 0, "nothing lived on replica 0 to migrate"
    fleet.kill_replica(1)
    fleet.run()
    assert fleet.stats()["num_lost_requests"] == 0
    kinds = {e["kind"] for e in obs.recorder.tail()}
    assert {"migrate", "replica_down", "failover"} <= kinds

    import json
    dump_path = tmp_path / "fleet_dump.json"
    dump_path.write_text(json.dumps(obs.dump(), default=str))
    spec = importlib.util.spec_from_file_location(
        "_trace_summary",
        Path(__file__).resolve().parents[1] / "tools" /
        "trace_summary.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    report = mod.summarize_file(str(dump_path))
    assert "-- fleet:" in report
    assert "replicas down" in report


def test_fleet_stats_surface(tiny_gpt):
    fleet = _fleet(tiny_gpt, n=2)
    stats = fleet.stats()
    for key in ("num_replicas", "replicas_alive", "num_failovers",
                "num_migrations", "num_lost_requests", "replicas",
                "tenants", "num_affinity_hits", "queue_depth"):
        assert key in stats
    assert stats["replicas"]["0"]["alive"] is True
    # the engine-side load surface the router polls
    ld = fleet.replicas[0].engine.load()
    assert set(ld) == {"queue_depth", "active_slots",
                       "ewma_prefill_dispatch_s",
                       "ewma_decode_dispatch_s", "blocks_allocatable"}


# ---------------------------------------------------------------------------
# review-hardening regressions
# ---------------------------------------------------------------------------


def test_cold_replica_backlog_weighs_neutral_not_zero(tiny_gpt):
    """Regression: a replica with no service EWMAs (cold/respawned)
    must weigh its backlog at the neutral 1.0 — a relative weight of
    0 made its queue invisible to placement and funneled every
    arrival at it."""
    fleet = _fleet(tiny_gpt, n=2)
    warm, cold = fleet.replicas[0].engine, fleet.replicas[1].engine
    warm._ewma_prefill_s = warm._ewma_decode_s = 0.01
    # warm replica: small backlog; cold replica: triple it
    warm.add_request(Request("w0", [1, 2, 3], max_new_tokens=2))
    for k in range(3):
        cold.add_request(Request(f"c{k}", [4 + k, 5, 6],
                                 max_new_tokens=2))
    # the cold replica's larger backlog must lose the placement
    ranked = fleet._ranked([7, 8, 9, 10])
    assert ranked[0][0] == 0


def test_retire_last_alive_replica_refuses(tiny_gpt):
    fleet = _fleet(tiny_gpt, n=1)
    fleet.add_request(_reqs(n=1, sampled=False)[0])
    with pytest.raises(ValueError, match="last alive replica"):
        fleet.drain_replica(0, retire=True)
    # nothing was harmed: the request still serves
    assert fleet.run(return_status=True)["r0"].status == "finished"
    # an IDLE last replica may retire
    fleet2 = _fleet(tiny_gpt, n=1)
    assert fleet2.drain_replica(0, retire=True) == 0
    assert fleet2.replicas[0].alive is False


def test_failover_preserves_streamed_tokens_of_uncheckpointed(tiny_gpt):
    """Regression: a SAMPLED request accepted after the last
    checkpoint (here: no checkpoint at all) that already streamed
    tokens must carry them through the fresh re-injection — the new
    arrival identity redraws only future tokens, so the delivered
    stream stays a prefix of the terminal result."""
    fleet = _fleet(tiny_gpt, n=2)   # no snapshot_interval_ticks
    fleet.add_request(Request(
        "s0", [3, 1, 4, 1, 5], max_new_tokens=8,
        sampling=SamplingParams(temperature=1.0, top_k=10)))
    streamed = []
    for _ in range(4):
        fleet.step()
        streamed += [tok for uid, tok, last
                     in fleet.pop_stream_events() if tok >= 0]
    assert streamed, "nothing streamed before the kill"
    fleet.kill_replica(fleet.owners()["s0"])
    res = fleet.run(return_status=True)
    assert res["s0"].tokens[:len(streamed)] == streamed
    assert fleet.stats()["num_reinjected_requests"] == 1


def test_stream_tokens_exactly_once_under_kill(tiny_gpt):
    """Regression: tokens a failover re-derivation replays (emitted
    after the checkpoint, streamed before the crash) are suppressed
    by the delivery watermark — per uid, the streamed token sequence
    equals the terminal result exactly, no duplicates."""
    fleet = _fleet(tiny_gpt, n=2, snapshot_interval_ticks=2)
    for r in _reqs(n=4, new=8):
        fleet.add_request(r)
    streamed = {}
    killed = False
    tick = 0
    while fleet.has_work:
        fleet.step()
        tick += 1
        # kill AFTER a checkpoint boundary with later ticks streamed,
        # so the checkpoint is genuinely stale
        if tick == 3 and not killed:
            fleet.kill_replica(fleet.owners()[
                next(iter(fleet.owners()))])
            killed = True
        for uid, tok, last in fleet.pop_stream_events():
            if tok >= 0:
                streamed.setdefault(uid, []).append(tok)
    assert killed
    res = fleet.run(return_status=True)
    for uid, toks in streamed.items():
        assert toks == res[uid].tokens, (
            f"{uid}: streamed {toks} != result {res[uid].tokens}")
    assert fleet.stats()["num_lost_requests"] == 0


def test_fleet_door_resident_charge_sums_across_replicas(tiny_gpt):
    """Regression: the fleet-wide max_resident_blocks quota must
    compare the tenant's resident charge SUMMED across replicas plus
    the request's worst case — not only the per-request footprint."""
    fleet = _fleet(tiny_gpt, n=2, fleet_kw=dict(
        tenant_quotas={"t": TenantQuota(max_resident_blocks=4)}))
    # 8-token prompt + 4 new = 3 blocks worst case: passes the
    # per-request check (3 <= 4)
    fleet.add_request(Request("a", list(range(1, 9)),
                              max_new_tokens=4, tenant="t"))
    fleet.step()     # admitted: the tenant now HOLDS blocks
    with pytest.raises(TenantThrottledError, match="resident"):
        fleet.add_request(Request("b", list(range(1, 9)),
                                  max_new_tokens=4, tenant="t"))
    res = fleet.run(return_status=True)
    assert res["a"].status == "finished"
    assert res["b"].status == "throttled"
    # charge drains with the residency: the same request is admissible
    # once "a" finished (its cached blocks hold no references)
    fleet.add_request(Request("c", list(range(1, 9)),
                              max_new_tokens=4, tenant="t"))
    assert fleet.run(return_status=True)["c"].status == "finished"


def test_failover_adopts_only_owned_checkpoint_results(tiny_gpt):
    """Regression: a stale checkpoint listing finished uids from
    already-delivered lifetimes must not resurrect them (or disown a
    reused uid now live elsewhere) — adoption is restricted to uids
    the dead replica still owns."""
    fleet = _fleet(tiny_gpt, n=2, snapshot_interval_ticks=1)
    fleet.add_request(Request("x", [1, 2, 3, 4], max_new_tokens=2))
    first = fleet.run(return_status=True)
    assert first["x"].status == "finished"
    # the dead replica's checkpoint still lists batch-1 "x" as
    # finished (it was undrained at checkpoint time); batch 2 reuses
    # the uid on the OTHER replica
    owner1 = 0
    fleet.add_request(Request("y", [9, 9, 9, 9, 9, 9, 9, 9],
                              max_new_tokens=4))
    # force the reused uid onto the survivor by loading replica 0
    fleet.add_request(Request("x", [5, 6, 7, 8], max_new_tokens=3))
    kill = owner1 if fleet.owners()["x"] != owner1 else 1
    assert fleet.owners()["x"] != kill
    fleet.kill_replica(kill)
    res = fleet.run(return_status=True)
    # the reused uid's result is the NEW lifetime's, not batch 1's
    assert len(res["x"].tokens) == 3
    assert fleet.stats()["num_lost_requests"] == 0


def test_soft_death_drains_stream_before_checkpoint(tiny_gpt):
    """Regression: an in-process replica death (exception escape)
    must collect the intact engine's buffered stream events before
    the failover checkpoint, or the delivery watermark anchors past
    tokens the consumer never received (a silent stream gap)."""
    faults = [FaultPlan([FaultSpec(site="decode", kind="crash",
                                   at=(3,))], seed=4), None]
    fleet = _fleet(tiny_gpt, n=2, faults=faults)
    fleet.add_request(Request(
        "g0", [2, 7, 1, 8], max_new_tokens=8,
        sampling=SamplingParams(temperature=1.0, top_k=10)))
    streamed = []
    while fleet.has_work:
        fleet.step()
        streamed += [tok for uid, tok, last
                     in fleet.pop_stream_events() if tok >= 0]
    res = fleet.run(return_status=True)
    assert fleet.stats()["num_replicas_down"] == 1
    # gapless and exactly-once: the streamed sequence IS the result
    assert streamed == res["g0"].tokens


def test_import_requests_anchors_observer_timeline(tiny_gpt):
    model, params = tiny_gpt
    obs = Observability(recorder_capacity=0, metrics=False)
    eng = InferenceEngine(model, params, EngineConfig(**ENGINE_KW),
                          obs=obs)
    eng.import_requests([{
        "uid": "mig", "prompt": [1, 2, 3], "max_new_tokens": 2,
        "sampling": {"temperature": 0.0, "top_k": 0, "top_p": 1.0},
        "generated": [], "arrival": 5,
    }])
    evs = obs.tracer.request_timeline("mig")
    assert any(e["type"] == "requeue" for e in evs)
    eng.run()


# ---------------------------------------------------------------------------
# the fuzz interleaving (satellite)
# ---------------------------------------------------------------------------


def test_fuzz_add_abort_kill_migrate_exactly_once(tiny_gpt):
    """Seeded fuzz over add/abort/kill/migrate/step: every accepted
    uid reaches EXACTLY ONE terminal status fleet-wide, the zero-lost
    gauge stays 0 throughout, and the surviving allocators stay
    exact."""
    rng = np.random.RandomState(1234)
    model, params = tiny_gpt
    fleet = FleetRouter(
        model, params,
        EngineConfig(**ENGINE_KW, snapshot_interval_ticks=2),
        FleetConfig(num_replicas=3, respawn=True))
    shared = list(rng.randint(1, 50, 8))
    accepted, uid = [], 0
    kills = 0
    for op_i in range(60):
        op = rng.rand()
        if op < 0.45:
            prompt = (list(shared) if rng.rand() < 0.5
                      else list(rng.randint(1, 50, rng.randint(3, 10))))
            samp = (SamplingParams(temperature=1.0, top_k=10)
                    if rng.rand() < 0.5 else SamplingParams())
            req = Request(f"f{uid}", prompt,
                          max_new_tokens=int(rng.randint(1, 6)),
                          sampling=samp)
            uid += 1
            if fleet.try_add(req):
                accepted.append(req.uid)
        elif op < 0.55 and accepted:
            fleet.abort(accepted[int(rng.randint(len(accepted)))])
        elif op < 0.62 and kills < 3:
            alive = [i for i, rep in enumerate(fleet.replicas)
                     if rep.alive]
            if len(alive) > 1:
                fleet.kill_replica(alive[int(rng.randint(len(alive)))])
                kills += 1
        elif op < 0.72:
            owners = fleet.owners()
            if owners:
                u = list(owners)[int(rng.randint(len(owners)))]
                fleet.migrate([u], owners[u])
        else:
            fleet.step()
        assert fleet.stats()["num_lost_requests"] == 0
    res = fleet.run(return_status=True)
    assert kills > 0, "fuzz never killed a replica"
    # exactly-once: every accepted uid has one terminal verdict
    assert set(res) >= set(accepted)
    terminal = {"finished", "timeout", "failed", "rejected",
                "throttled", "cancelled"}
    assert all(r.status in terminal for r in res.values())
    stats = fleet.stats()
    assert stats["num_lost_requests"] == 0
    for rep in fleet.replicas:
        if rep.alive and rep.engine is not None:
            rep.engine.check_allocator_integrity()


def test_fuzz_with_corruption_faults_zero_undetected(tiny_gpt):
    """The 60-op fuzz under seeded CORRUPTION plans covering every
    checksum point (spill writes/reads, checkpoints, migration records
    both directions), with independent test-side oracles wrapped
    around every consumption path: the zero-lost gauge reads 0 after
    every op, and ZERO corrupted artifacts are consumed undetected —
    every spill payload an engine admits hashes to the clean bytes its
    put recorded, and every migration record an import ACCEPTS matches
    the record the caller sent (a corruption either refused/discarded
    — caught — or never consumed)."""
    from apex_tpu.utils.integrity import payload_checksum

    rng = np.random.RandomState(4321)
    model, params = tiny_gpt
    plans = [FaultPlan([
        FaultSpec(site="spill_put", kind="corrupt", every=3),
        FaultSpec(site="spill_get", kind="corrupt", every=4),
        FaultSpec(site="checkpoint", kind="corrupt", every=2),
        FaultSpec(site="export", kind="corrupt", every=2),
        FaultSpec(site="import", kind="corrupt", every=3),
    ], seed=100 + i) for i in range(3)]
    ekw = dict(ENGINE_KW, num_blocks=12, spill_max_bytes=1 << 20,
               snapshot_interval_ticks=2, scrub_interval_ticks=3)
    fleet = FleetRouter(
        model, params, EngineConfig(**ekw),
        FleetConfig(num_replicas=3, respawn=True),
        faults=plans)
    truth: dict = {}    # chain hash -> clean payload checksum

    def wrap_store(store):
        orig_put, orig_pop = store.put, store.pop

        def put(h, payload, tenant="default"):
            truth[h] = payload_checksum(payload)  # the TRUE bytes
            return orig_put(h, payload, tenant=tenant)

        def pop(h):
            out = orig_pop(h)
            if out is not None:
                assert payload_checksum(out) == truth[h], (
                    f"UNDETECTED corrupt spill admission for {h}")
            return out

        store.put, store.pop = put, pop

    def wrap_import(eng):
        orig = eng.import_requests

        def import_requests(records):
            want = {r["uid"]: ([int(t) for t in r["prompt"]],
                               [int(t) for t in r.get("generated", ())])
                    for r in records}
            n = orig(records)
            for entry in eng.waiting:
                got = want.get(entry.request.uid)
                if got is not None:
                    assert ([int(t) for t in entry.request.prompt],
                            [int(t) for t in entry.generated]) == got, (
                        "UNDETECTED corrupt import accepted")
            return n

        eng.import_requests = import_requests

    def instrument(rep):
        if rep.engine is None:
            return
        if rep.engine.spill is not None:
            wrap_store(rep.engine.spill)
        wrap_import(rep.engine)

    for rep in fleet.replicas:
        instrument(rep)
    shared = list(rng.randint(1, 50, 8))
    accepted, uid, kills = [], 0, 0
    for op_i in range(60):
        op = rng.rand()
        if op < 0.45:
            prompt = (list(shared) if rng.rand() < 0.5
                      else list(rng.randint(1, 50, rng.randint(3, 10))))
            samp = (SamplingParams(temperature=1.0, top_k=10)
                    if rng.rand() < 0.5 else SamplingParams())
            req = Request(f"z{uid}", prompt,
                          max_new_tokens=int(rng.randint(1, 6)),
                          sampling=samp)
            uid += 1
            if fleet.try_add(req):
                accepted.append(req.uid)
        elif op < 0.55 and accepted:
            fleet.abort(accepted[int(rng.randint(len(accepted)))])
        elif op < 0.62 and kills < 3:
            alive = [i for i, rep in enumerate(fleet.replicas)
                     if rep.alive]
            if len(alive) > 1:
                victim = alive[int(rng.randint(len(alive)))]
                fleet.kill_replica(victim)
                instrument(fleet.replicas[victim])   # the respawn
                kills += 1
        elif op < 0.72:
            owners = fleet.owners()
            if owners:
                u = list(owners)[int(rng.randint(len(owners)))]
                fleet.migrate([u], owners[u])
        else:
            fleet.step()
        assert fleet.stats()["num_lost_requests"] == 0
    res = fleet.run(return_status=True)
    assert kills > 0
    assert set(res) >= set(accepted)
    stats = fleet.stats()
    assert stats["num_lost_requests"] == 0
    # the chaos genuinely fired AND was genuinely caught somewhere:
    # refused imports, corrupt checkpoints, or spill discards
    detections = (
        stats["num_refused_imports"] + stats["num_corrupt_checkpoints"]
        + sum(rep.engine.stats()["num_corruptions_detected"]
              for rep in fleet.replicas
              if rep.alive and rep.engine is not None))
    assert detections > 0, "corruption plan never detected anything"
    for rep in fleet.replicas:
        if rep.alive and rep.engine is not None:
            rep.engine.check_allocator_integrity()
