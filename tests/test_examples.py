"""Examples tier smoke tests (upstream analog: tests/L1 driving
examples/imagenet/main_amp.py through opt levels, SURVEY.md §4) — run
in-process on the CPU sim with tiny step counts."""

import sys

import pytest

# Example scripts run real (tiny) training loops - the suite's
# heaviest tier; fast CI runs -m "not slow".
pytestmark = pytest.mark.slow


def _run(module_main, argv):
    old = sys.argv
    sys.argv = argv
    try:
        return module_main()
    finally:
        sys.argv = old


@pytest.mark.parametrize("opt_level", ["O0", "O1", "O2", "O3"])
def test_train_mnist_all_opt_levels(opt_level, capsys):
    from examples.train_mnist import main

    final = _run(main, ["train_mnist", "--opt-level", opt_level,
                        "--steps", "25", "--batch-size", "64"])
    out = capsys.readouterr().out
    assert final < 0.5  # separable blobs: loss collapses fast
    if opt_level in ("O1", "O2"):
        # dynamic scaling default: the injected inf must print the line
        assert "Gradient overflow.  Skipping step, loss scaler 0" in out


def test_train_mnist_checkpoint_resume(tmp_path, capsys):
    from examples.train_mnist import main

    d = str(tmp_path / "ck")
    _run(main, ["train_mnist", "--steps", "10", "--inject-inf-at", "-1",
                "--ckpt-dir", d])
    _run(main, ["train_mnist", "--steps", "10", "--inject-inf-at", "-1",
                "--ckpt-dir", d])
    out = capsys.readouterr().out
    assert "resumed from step 10" in out


def test_train_bert_tiny(capsys):
    from examples.train_bert import main

    _run(main, ["train_bert", "--config", "tiny", "--steps", "3",
                "--batch-size", "2", "--seq", "64"])
    out = capsys.readouterr().out
    assert "ms/step" in out


def test_train_long_context(capsys):
    from examples.train_long_context import main

    _run(main, ["train_long_context", "--seq", "256", "--steps", "4",
                "--hidden", "64", "--vocab", "128"])
    out = capsys.readouterr().out
    assert "tokens/s" in out and "cp=8" in out


def test_train_resnet_ddp_syncbn(capsys):
    """The imagenet main_amp analog: amp O2 + DDP + SyncBN ResNet trains
    on the 8-replica mesh and improves top-1 on separable data."""
    from examples.train_resnet import main

    final = _run(main, ["train_resnet", "--arch", "tiny", "--steps", "12",
                        "--batch-size", "32"])
    out = capsys.readouterr().out
    assert "replicas=8" in out
    assert "top1" in out
    assert final < 2.0  # down from ~2.3 (ln 10) on 10 separable classes


def test_train_resnet_delay_allreduce_local_bn(capsys):
    from examples.train_resnet import main

    _run(main, ["train_resnet", "--arch", "tiny", "--steps", "4",
                "--batch-size", "16", "--no-sync-bn",
                "--delay-allreduce", "--opt-level", "O1"])
    out = capsys.readouterr().out
    assert "final loss" in out
