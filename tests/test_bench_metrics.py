"""The bench's secondary metrics must be regression-WORTHY (round-3
verdict #3): a deliberately-introduced regression must visibly move the
recorded value. These tests drive the measurement helpers themselves —
the HLO collective counter against a program with a doubled sync, and
the marginal timer's noise guard."""

import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import bench  # noqa: E402  (repo-root module)


def _compiled_hlo(sync_twice):
    mesh = jax.make_mesh((8,), ("data",))

    def step(p, x):
        def loss(p):
            return jnp.mean((x @ p) ** 2)

        g = jax.grad(loss)(p)
        g = jax.lax.psum(g, "data")
        if sync_twice:  # the deliberate regression: a redundant sync
            g = jax.lax.psum(g, "data") / 8.0
        return p - 1e-3 * g

    p = jnp.ones((64, 16))
    x = jnp.ones((8 * 2, 64))
    f = jax.jit(jax.shard_map(step, mesh=mesh,
                              in_specs=(P(), P("data")), out_specs=P()))
    return f.lower(p, x).compile().as_text()


def test_allreduce_counter_catches_doubled_sync():
    ops1, bytes1 = bench.count_allreduce_bytes(_compiled_hlo(False))
    ops2, bytes2 = bench.count_allreduce_bytes(_compiled_hlo(True))
    assert ops1 >= 1 and bytes1 >= 64 * 16 * 4
    # the deliberate regression must move the metric
    assert bytes2 > bytes1
    assert ops2 > ops1


def test_allreduce_counter_parses_tuple_shapes():
    text = (
        "%ar = (f32[32]{0}, f32[32]{0}, s32[]) "
        "all-reduce(%a, %b, %c), replica_groups={}\n"
        "%other = f32[8]{0} add(%x, %y)\n"
        "%ar2 = bf16[4,128]{1,0} all-reduce-start(%d)\n"
    )
    ops, total = bench.count_allreduce_bytes(text)
    assert ops == 2
    assert total == 32 * 4 + 32 * 4 + 4 + 4 * 128 * 2


def test_marginal_time_discards_noise_corrupted_windows():
    """A latency spike in a small window would produce a negative
    marginal; the guard must discard it and keep the clean pair."""
    calls = {"n": 0}
    t = {"now": 0.0}

    def advance(n):
        t["now"] += n * 0.010  # 10 ms true step

    spikes = iter([0.200, 0.0, 0.0, 0.0])  # spike hits window 1's fetch

    def fetch():
        t["now"] += 0.100 + next(spikes, 0.0)
        return 0.0

    import time as time_mod

    real = time_mod.perf_counter
    time_mod.perf_counter = lambda: t["now"]
    try:
        dt = bench.marginal_time(advance, fetch, iters=8, windows=2)
    finally:
        time_mod.perf_counter = real
    np.testing.assert_allclose(dt, 0.010, rtol=1e-6)


def test_marginal_time_all_windows_corrupted_falls_back_positive():
    t = {"now": 0.0}

    def advance(n):
        t["now"] += n * 0.010

    spikes = iter([0.500, 0.0, 0.500, 0.0])  # every small window spiked

    def fetch():
        t["now"] += 0.100 + next(spikes, 0.0)
        return 0.0

    import time as time_mod

    real = time_mod.perf_counter
    time_mod.perf_counter = lambda: t["now"]
    try:
        dt = bench.marginal_time(advance, fetch, iters=8, windows=2)
    finally:
        time_mod.perf_counter = real
    assert dt > 0
