"""The bench's secondary metrics must be regression-WORTHY (round-3
verdict #3): a deliberately-introduced regression must visibly move the
recorded value. These tests drive the measurement helpers themselves —
the HLO collective counter against a program with a doubled sync, and
the marginal timer's noise guard."""

import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import bench  # noqa: E402  (repo-root module)


def _compiled_hlo(sync_twice):
    mesh = jax.make_mesh((8,), ("data",))

    def step(p, x):
        def loss(p):
            return jnp.mean((x @ p) ** 2)

        g = jax.grad(loss)(p)
        g = jax.lax.psum(g, "data")
        if sync_twice:  # the deliberate regression: a redundant sync
            g = jax.lax.psum(g, "data") / 8.0
        return p - 1e-3 * g

    p = jnp.ones((64, 16))
    x = jnp.ones((8 * 2, 64))
    f = jax.jit(jax.shard_map(step, mesh=mesh,
                              in_specs=(P(), P("data")), out_specs=P()))
    return f.lower(p, x).compile().as_text()


def test_allreduce_counter_catches_doubled_sync():
    ops1, bytes1 = bench.count_allreduce_bytes(_compiled_hlo(False))
    ops2, bytes2 = bench.count_allreduce_bytes(_compiled_hlo(True))
    assert ops1 >= 1 and bytes1 >= 64 * 16 * 4
    # the deliberate regression must move the metric
    assert bytes2 > bytes1
    assert ops2 > ops1


def test_allreduce_counter_parses_tuple_shapes():
    text = (
        "%ar = (f32[32]{0}, f32[32]{0}, s32[]) "
        "all-reduce(%a, %b, %c), replica_groups={}\n"
        "%other = f32[8]{0} add(%x, %y)\n"
        "%ar2 = bf16[4,128]{1,0} all-reduce-start(%d)\n"
    )
    ops, total = bench.count_allreduce_bytes(text)
    assert ops == 2
    assert total == 32 * 4 + 32 * 4 + 4 + 4 * 128 * 2


def test_marginal_time_discards_noise_corrupted_windows():
    """A latency spike in a small window would produce a negative
    marginal; the guard must discard it and keep the clean pair."""
    calls = {"n": 0}
    t = {"now": 0.0}

    def advance(n):
        t["now"] += n * 0.010  # 10 ms true step

    spikes = iter([0.200, 0.0, 0.0, 0.0])  # spike hits window 1's fetch

    def fetch():
        t["now"] += 0.100 + next(spikes, 0.0)
        return 0.0

    import time as time_mod

    real = time_mod.perf_counter
    time_mod.perf_counter = lambda: t["now"]
    try:
        dt = bench.marginal_time(advance, fetch, iters=8, windows=2)
    finally:
        time_mod.perf_counter = real
    np.testing.assert_allclose(dt, 0.010, rtol=1e-6)


def test_marginal_time_all_windows_corrupted_falls_back_positive():
    t = {"now": 0.0}

    def advance(n):
        t["now"] += n * 0.010

    spikes = iter([0.500, 0.0, 0.500, 0.0])  # every small window spiked

    def fetch():
        t["now"] += 0.100 + next(spikes, 0.0)
        return 0.0

    import time as time_mod

    real = time_mod.perf_counter
    time_mod.perf_counter = lambda: t["now"]
    try:
        dt = bench.marginal_time(advance, fetch, iters=8, windows=2)
    finally:
        time_mod.perf_counter = real
    assert dt > 0


# ---------------------------------------------------------------------------
# round 5: the generalized collective audit (apex_tpu.utils.hlo_audit)
# ---------------------------------------------------------------------------

def _lower_shmap(fn, in_specs, out_specs, *args, n=8, axes=("data",)):
    mesh = jax.make_mesh((n,), axes)
    f = jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_vma=False))
    return f.lower(*args).compile().as_text()


def test_collective_stats_identifies_each_kind():
    """Every collective family must be counted under its own key (the
    advisor-r4 finding: an all-reduce-only counter reads a grad sync
    rewritten as reduce-scatter + all-gather as an improvement)."""
    from apex_tpu.utils.hlo_audit import collective_stats

    x = jnp.ones((8 * 8, 128))

    hlo = _lower_shmap(lambda x: jax.lax.psum(x, "data"),
                       P("data"), P("data"), x)
    assert collective_stats(hlo)["all-reduce"]["ops"] >= 1

    hlo = _lower_shmap(lambda x: jax.lax.psum_scatter(
        x, "data", scatter_dimension=0, tiled=True),
        P("data"), P("data"), x)
    s = collective_stats(hlo)
    assert s["reduce-scatter"]["ops"] >= 1

    hlo = _lower_shmap(lambda x: jax.lax.all_gather(
        x, "data", axis=0, tiled=True), P("data"), P(), x)
    assert collective_stats(hlo)["all-gather"]["ops"] >= 1

    hlo = _lower_shmap(lambda x: jax.lax.all_to_all(
        x, "data", split_axis=1, concat_axis=0, tiled=True),
        P("data"), P("data", None), x)
    assert collective_stats(hlo)["all-to-all"]["ops"] >= 1

    perm = [(i, (i + 1) % 8) for i in range(8)]
    hlo = _lower_shmap(lambda x: jax.lax.ppermute(x, "data", perm),
                       P("data"), P("data"), x)
    assert collective_stats(hlo)["collective-permute"]["ops"] >= 1


def test_collective_stats_total_and_bytes():
    from apex_tpu.utils.hlo_audit import collective_stats

    text = (
        "%ar = (f32[32]{0}, s32[]) all-reduce(%a, %b), replica_groups={}\n"
        "%ag = bf16[64,128]{1,0} all-gather-start(%c)\n"
        "%rs = f32[8]{0} reduce-scatter(%d)\n"
        "%cp = f32[16]{0} collective-permute(%e)\n"
        "%a2a = f32[4,4]{1,0} all-to-all(%f)\n"
        "%noise = f32[9]{0} add(%x, %y)\n"
    )
    s = collective_stats(text)
    assert s["all-reduce"] == {"ops": 1, "bytes": 32 * 4 + 4}
    assert s["all-gather"] == {"ops": 1, "bytes": 64 * 128 * 2}
    assert s["reduce-scatter"] == {"ops": 1, "bytes": 32}
    assert s["collective-permute"] == {"ops": 1, "bytes": 64}
    assert s["all-to-all"] == {"ops": 1, "bytes": 64}
    assert s["total"]["ops"] == 5


def test_collective_stats_complex_f8_and_unknown_dtypes():
    """Advisor r5 #2: c64/c128 and f8 payloads must be counted at their
    true element sizes, and an unrecognized dtype must WARN instead of
    silently assuming 4 bytes."""
    import warnings

    from apex_tpu.utils.hlo_audit import collective_stats

    text = (
        "%ar = c64[8,4]{1,0} all-reduce(%a), replica_groups={}\n"
        "%ag = c128[2]{0} all-gather(%b)\n"
        "%rs = f8e4m3fn[16]{0} reduce-scatter(%c)\n"
        "%cp = f8e5m2[32]{0} collective-permute(%d)\n"
    )
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # exact sizes: no warning fires
        s = collective_stats(text)
    assert s["all-reduce"]["bytes"] == 8 * 4 * 8
    assert s["all-gather"]["bytes"] == 2 * 16
    assert s["reduce-scatter"]["bytes"] == 16
    assert s["collective-permute"]["bytes"] == 32

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        collective_stats("%x = zz9[4]{0} all-reduce(%a)\n")
    assert any("unknown HLO dtype" in str(x.message) for x in w)


def test_collective_audit_catches_migrated_grad_sync():
    """The deliberate regression for the ddp metric's companion field:
    replace the all-reduce grad sync with reduce-scatter + all-gather
    (same bytes moved, zero all-reduce bytes). The generalized stats
    must expose the migrated traffic."""
    from apex_tpu.utils.hlo_audit import collective_stats

    p = jnp.ones((64, 16))
    x = jnp.ones((8 * 2, 64))

    def step(migrated, p, x):
        g = jax.grad(lambda p: jnp.mean((x @ p) ** 2))(p)
        if migrated:
            shard = jax.lax.psum_scatter(
                g.reshape(-1), "data", scatter_dimension=0, tiled=True)
            g = jax.lax.all_gather(shard, "data", axis=0,
                                   tiled=True).reshape(g.shape)
        else:
            g = jax.lax.psum(g, "data")
        return p - 1e-3 * g

    import functools

    def lower(migrated):
        mesh = jax.make_mesh((8,), ("data",))
        f = jax.jit(jax.shard_map(
            functools.partial(step, migrated), mesh=mesh,
            in_specs=(P(), P("data")), out_specs=P(),
            check_vma=False))  # all_gather output replication isn't
        return f.lower(p, x).compile().as_text()  # statically inferable

    hlo_ar, hlo_mig = lower(False), lower(True)
    s_ar, s_mig = collective_stats(hlo_ar), collective_stats(hlo_mig)
    # the naive all-reduce-only view: migration reads as "improvement"
    assert s_mig["all-reduce"]["bytes"] < s_ar["all-reduce"]["bytes"]
    # the generalized view catches it
    migrated_bytes = (s_mig["reduce-scatter"]["bytes"]
                      + s_mig["all-gather"]["bytes"])
    assert migrated_bytes >= 64 * 16 * 4


def test_ulysses_attention_all_to_all_count():
    """Program-shape contract of the Ulysses layer (SURVEY §2.3 CP row):
    4 all_to_alls in forward (q, k, v to heads; out back to sequence)
    and 4 in backward (AD of all_to_all is its inverse)."""
    from apex_tpu.ops.ulysses_attention import ulysses_attention
    from apex_tpu.utils.hlo_audit import collective_stats

    B, H, S, D = 2, 4, 16, 8
    rng = np.random.RandomState(0)
    # distinct q/k/v: identical operands would let CSE merge their
    # all_to_alls and undercount the real model's program shape
    q, k, v = (jnp.asarray(rng.randn(B, H, S // 2, D).astype("f4"))
               for _ in range(3))

    def step(q, k, v):
        def loss(q, k, v):
            o = ulysses_attention(q, k, v, axis_name="context",
                                  causal=True, scale=0.3)
            return jnp.sum(o.astype(jnp.float32) ** 2)
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    mesh = jax.make_mesh((2,), ("context",), devices=jax.devices()[:2])
    spec = P(None, None, "context")
    f = jax.jit(jax.shard_map(step, mesh=mesh, in_specs=(spec,) * 3,
                              out_specs=(spec,) * 3))
    hlo = f.lower(q, k, v).compile().as_text()
    assert collective_stats(hlo)["all-to-all"]["ops"] == 8
