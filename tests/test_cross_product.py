"""L1 cross-product analog (reference: ``tests/L1/cross_product/run.sh``
+ ``compare.py`` (U), SURVEY.md §4): sweep opt_level x loss_scale over
the same model/data/seed and diff the loss curves between configs. The
reference asserts the mixed-precision recipes track the fp32 recipe; so
does this — O0 is the anchor, every other config must follow its curve
within a bf16-sized tolerance and reach the same converged loss."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.amp as amp
from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.optimizers import FusedAdam

import flax.linen as nn

STEPS = 40


class Net(nn.Module):
    """Small net WITH a norm layer so keep_batchnorm_fp32 has teeth."""

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(32, param_dtype=jnp.float32)(x)
        x = FusedLayerNorm(32)(x)
        x = nn.relu(x)
        return nn.Dense(4, param_dtype=jnp.float32)(x)


def _data():
    rng = np.random.RandomState(0)
    centers = rng.randn(4, 16) * 3
    xs = np.concatenate([c + rng.randn(32, 16) for c in centers])
    ys = np.repeat(np.arange(4), 32)
    return jnp.asarray(xs, jnp.float32), jnp.asarray(ys)


def _curve(opt_level, loss_scale=None, keep_batchnorm_fp32=None):
    xs, ys = _data()
    model = Net()
    params = model.init(jax.random.PRNGKey(1), xs)["params"]
    kw = {}
    if loss_scale is not None:
        kw["loss_scale"] = loss_scale
    if keep_batchnorm_fp32 is not None:
        kw["keep_batchnorm_fp32"] = keep_batchnorm_fp32
    params, opt, handle = amp.initialize(
        params, FusedAdam(lr=1e-2), opt_level=opt_level, verbosity=0, **kw)
    ost = opt.init(params)
    sst = handle.init_state()

    @jax.jit
    def step(params, ost, sst):
        def loss_fn(p):
            logits = model.apply({"params": p}, xs).astype(jnp.float32)
            lp = jax.nn.log_softmax(logits)
            return -jnp.mean(jnp.take_along_axis(lp, ys[:, None], 1))

        (loss, found), grads = handle.value_and_grad(loss_fn, sst)(params)
        p2, o2 = opt.step(grads, ost, params, skip_if=found)
        return p2, o2, handle.update_scale(sst, found), loss

    curve = []
    for _ in range(STEPS):
        params, ost, sst, loss = step(params, ost, sst)
        curve.append(float(loss))
    return np.asarray(curve)


@pytest.fixture(scope="module")
def anchor():
    return _curve("O0")


CONFIGS = [
    ("O1", None, None),
    ("O1", 128.0, None),
    ("O2", None, None),
    ("O2", 128.0, None),
    ("O2", None, False),   # cast norms too
    ("O3", None, None),
    ("O3", None, True),    # O3 + fp32 norms (the documented O3 tweak)
]


@pytest.mark.parametrize("opt_level,loss_scale,keep_bn", CONFIGS)
def test_curves_track_fp32_anchor(anchor, opt_level, loss_scale, keep_bn):
    curve = _curve(opt_level, loss_scale, keep_bn)
    assert np.all(np.isfinite(curve))
    # compare.py contract: trajectories agree within mixed-precision
    # noise at every step, and converge to the anchor's level
    np.testing.assert_allclose(curve, anchor, atol=0.08)
    assert curve[-1] < anchor[0] * 0.2  # actually trained
    assert abs(curve[-1] - anchor[-1]) < 0.05
