"""Fused optimizer tests vs independent references (upstream analog:
tests/L0/run_optimizers/test_fused_optimizer.py and test_lamb.py —
FusedAdam vs torch.optim.Adam, FusedLAMB vs an in-test reference LAMB;
here the references are optax and hand-rolled numpy, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu.optimizers import (
    FusedAdagrad,
    FusedAdam,
    FusedLAMB,
    FusedNovoGrad,
    FusedSGD,
)


def _params(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "layer1": {"kernel": jnp.asarray(rng.randn(8, 8).astype("float32")),
                   "bias": jnp.asarray(rng.randn(8).astype("float32"))},
        "layer2": {"kernel": jnp.asarray(rng.randn(8, 4).astype("float32"))},
    }


def _grads(seed=1):
    return _params(seed)


def test_fused_adam_matches_optax_adamw():
    params = _params()
    grads = _grads()
    opt = FusedAdam(lr=1e-2, weight_decay=0.01, adam_w_mode=True)
    st = opt.init(params)

    ref = optax.adamw(1e-2, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.01)
    ref_st = ref.init(params)
    ref_params = params

    p = params
    for _ in range(5):
        p, st = opt.step(grads, st, p)
        upd, ref_st = ref.update(grads, ref_st, ref_params)
        ref_params = optax.apply_updates(ref_params, upd)

    for a, b in zip(jax.tree.leaves(p), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)


def test_fused_adam_l2_mode_matches_optax_adam_with_l2():
    params = _params()
    grads = _grads()
    opt = FusedAdam(lr=1e-2, weight_decay=0.1, adam_w_mode=False)
    st = opt.init(params)
    p, st = opt.step(grads, st, p if (p := params) is not None else params)

    # reference: grad + wd*param into plain adam
    ref = optax.adam(1e-2)
    ref_st = ref.init(params)
    g2 = jax.tree.map(lambda g, q: g + 0.1 * q, grads, params)
    upd, _ = ref.update(g2, ref_st, params)
    ref_p = optax.apply_updates(params, upd)
    for a, b in zip(jax.tree.leaves(p), jax.tree.leaves(ref_p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)


def test_fused_adam_amsgrad_raises():
    with pytest.raises(RuntimeError):
        FusedAdam(amsgrad=True)


def test_fused_adam_skip_if_freezes_everything():
    params = _params()
    grads = _grads()
    opt = FusedAdam(lr=1e-2)
    st = opt.init(params)
    p2, st2 = opt.step(grads, st, params, skip_if=jnp.asarray(True))
    assert int(st2.step) == 0  # step count does not advance on skip
    for a, b in zip(jax.tree.leaves(p2), jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fused_adam_master_weights_bf16():
    """O2 flow: bf16 model params, fp32 masters carried in optimizer state.
    Master accumulates small updates that bf16 alone would lose."""
    params = {"w": jnp.ones((64,), jnp.bfloat16)}
    opt = FusedAdam(lr=1e-5).with_master_weights()
    st = opt.init(params)
    assert st.master["w"].dtype == jnp.float32
    grads = {"w": jnp.full((64,), 1.0, jnp.bfloat16)}
    p = params
    for _ in range(3):
        p, st = opt.step(grads, st, p)
    assert p["w"].dtype == jnp.bfloat16
    assert float(st.master["w"][0]) < 1.0  # master moved at fp32 resolution


def test_fused_lamb_matches_reference_lamb():
    """Hand-rolled reference LAMB (the upstream test_lamb.py pattern)."""
    params = _params()
    grads = _grads()
    lr, b1, b2, eps, wd = 1e-2, 0.9, 0.999, 1e-6, 0.01
    opt = FusedLAMB(lr=lr, betas=(b1, b2), eps=eps, weight_decay=wd,
                    max_grad_norm=0.0)  # no clipping for the simple ref
    st = opt.init(params)
    p, st = opt.step(grads, st, params)

    # reference
    leaves_p = [np.asarray(x) for x in jax.tree.leaves(params)]
    leaves_g = [np.asarray(x) for x in jax.tree.leaves(grads)]
    out = []
    for q, g in zip(leaves_p, leaves_g):
        m = (1 - b1) * g
        v = (1 - b2) * g * g
        bc1, bc2 = 1 - b1, 1 - b2
        upd = (m / bc1) / (np.sqrt(v / bc2) + eps) + wd * q
        w_norm = np.linalg.norm(q)
        u_norm = np.linalg.norm(upd)
        ratio = w_norm / u_norm if (w_norm > 0 and u_norm > 0) else 1.0
        out.append(q - lr * ratio * upd)

    for a, b in zip(jax.tree.leaves(p), out):
        np.testing.assert_allclose(np.asarray(a), b, rtol=1e-4, atol=1e-6)


def test_fused_lamb_grad_clipping_engages():
    params = {"w": jnp.ones((4,))}
    big = {"w": jnp.full((4,), 1000.0)}
    opt = FusedLAMB(lr=1e-2, max_grad_norm=1.0, weight_decay=0.0)
    st = opt.init(params)
    p_clip, _ = opt.step(big, st, params)
    opt_noclip = opt.replace(max_grad_norm=0.0)
    p_noclip, _ = opt_noclip.step(big, opt_noclip.init(params), params)
    # same direction; clipped ratio identical here due to trust ratio
    # normalization, but moments must differ
    assert np.isfinite(np.asarray(p_clip["w"])).all()
    assert np.isfinite(np.asarray(p_noclip["w"])).all()


def test_fused_lamb_no_decay_is_plain_adam_step():
    """Reference semantics: without weight decay (and without use_nvlamb)
    the trust ratio is NOT applied."""
    params = {"w": jnp.full((4,), 10.0)}
    grads = {"w": jnp.full((4,), 1.0)}
    lr, b1, b2, eps = 1e-2, 0.9, 0.999, 1e-6
    opt = FusedLAMB(lr=lr, betas=(b1, b2), eps=eps, weight_decay=0.0,
                    max_grad_norm=0.0)
    p, _ = opt.step(grads, opt.init(params), params)
    # plain adam first step: update ~= 1 (m/bc1)/(sqrt(v/bc2)+eps)
    upd = ((1 - b1) / (1 - b1)) / (np.sqrt(1.0) + eps)
    np.testing.assert_allclose(np.asarray(p["w"]), 10.0 - lr * upd, rtol=1e-5)


def test_fused_lamb_nvlamb_applies_ratio_without_decay():
    params = {"w": jnp.full((4,), 10.0)}
    grads = {"w": jnp.full((4,), 1.0)}
    opt = FusedLAMB(lr=1e-2, weight_decay=0.0, max_grad_norm=0.0, use_nvlamb=True)
    p, _ = opt.step(grads, opt.init(params), params)
    p_ref, _ = opt.replace(use_nvlamb=False).step(grads, opt.init(params), params)
    assert not np.allclose(np.asarray(p["w"]), np.asarray(p_ref["w"]))


def test_adagrad_and_novograd_master_weights_update():
    """Masters must actually move under O2 (review regression)."""
    from apex_tpu.optimizers import FusedAdagrad, FusedNovoGrad

    params = {"w": jnp.ones((64,), jnp.bfloat16)}
    grads = {"w": jnp.full((64,), 0.01, jnp.bfloat16)}
    for opt in (FusedAdagrad(lr=1e-5).with_master_weights(),
                FusedNovoGrad(lr=1e-5).with_master_weights()):
        st = opt.init(params)
        p, st = opt.step(grads, st, params)
        assert st.master["w"].dtype == jnp.float32
        assert float(st.master["w"][0]) != 1.0, type(opt).__name__
        assert p["w"].dtype == jnp.bfloat16


def test_novograd_init_zero_changes_first_step():
    from apex_tpu.optimizers import FusedNovoGrad

    params = {"w": jnp.ones((4,))}
    grads = {"w": jnp.full((4,), 2.0)}
    a = FusedNovoGrad(lr=0.1, init_zero=False, bias_correction=False)
    b = FusedNovoGrad(lr=0.1, init_zero=True, bias_correction=False)
    pa, _ = a.step(grads, a.init(params), params)
    pb, _ = b.step(grads, b.init(params), params)
    assert not np.allclose(np.asarray(pa["w"]), np.asarray(pb["w"]))


def test_fused_sgd_matches_optax_sgd_momentum():
    params = _params()
    grads = _grads()
    opt = FusedSGD(lr=0.1, momentum=0.9)
    st = opt.init(params)
    ref = optax.sgd(0.1, momentum=0.9)
    ref_st = ref.init(params)

    p, ref_p = params, params
    for _ in range(4):
        p, st = opt.step(grads, st, p)
        upd, ref_st = ref.update(grads, ref_st, ref_p)
        ref_p = optax.apply_updates(ref_p, upd)
    for a, b in zip(jax.tree.leaves(p), jax.tree.leaves(ref_p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_fused_sgd_nesterov_validation():
    with pytest.raises(ValueError):
        FusedSGD(lr=0.1, nesterov=True, momentum=0.0)


def test_fused_adagrad_matches_reference():
    params = {"w": jnp.asarray(np.random.RandomState(0).randn(16).astype("float32"))}
    grads = {"w": jnp.asarray(np.random.RandomState(1).randn(16).astype("float32"))}
    opt = FusedAdagrad(lr=0.1, eps=1e-10)
    st = opt.init(params)
    p, st = opt.step(grads, st, params)

    h = np.asarray(grads["w"]) ** 2
    ref = np.asarray(params["w"]) - 0.1 * np.asarray(grads["w"]) / (np.sqrt(h) + 1e-10)
    np.testing.assert_allclose(np.asarray(p["w"]), ref, rtol=1e-5)


def test_fused_novograd_first_step_normalizes_by_grad_norm():
    params = {"w": jnp.ones((4,))}
    grads = {"w": jnp.full((4,), 2.0)}
    opt = FusedNovoGrad(lr=0.1, betas=(0.95, 0.98), weight_decay=0.0,
                        bias_correction=False)
    st = opt.init(params)
    p, st = opt.step(grads, st, params)
    # step 1: v = ||g||^2 = 16, denom = 4; g/denom = 0.5; m = beta3*g' = .05*0.5
    gnorm = 4.0
    m = (1 - 0.95) * (2.0 / gnorm)
    ref = 1.0 - 0.1 * m
    np.testing.assert_allclose(np.asarray(p["w"]), np.full((4,), ref), rtol=1e-5)
    np.testing.assert_allclose(float(st.exp_avg_sq[0]), 16.0, rtol=1e-5)


def test_as_optax_adapter():
    params = _params()
    grads = _grads()
    tx = FusedAdam(lr=1e-2).as_optax()
    st = tx.init(params)
    upd, st = tx.update(grads, st, params)
    p = optax.apply_updates(params, upd)

    opt = FusedAdam(lr=1e-2)
    p_ref, _ = opt.step(grads, opt.init(params), params)
    for a, b in zip(jax.tree.leaves(p), jax.tree.leaves(p_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7)


def test_optimizer_step_inside_jit_with_amp():
    """Integration: amp scaler + FusedAdam step-skip inside one jit."""
    import apex_tpu.amp as amp

    params = _params()
    opt = FusedAdam(lr=1e-2)
    st = opt.init(params)
    scaler = amp.LossScaler()
    sst = scaler.init()

    @jax.jit
    def step(p, ost, sst, bomb):
        def loss_fn(q):
            return sum(jnp.sum(x ** 2) for x in jax.tree.leaves(q)) * bomb

        (loss, found), grads = scaler.value_and_grad(loss_fn, sst)(p)
        p2, ost2 = opt.step(grads, ost, p, skip_if=found)
        return p2, ost2, scaler.update(sst, found), loss

    p, st, sst, _ = step(params, st, sst, jnp.asarray(1.0))
    assert int(st.step) == 1
    p, st, sst, _ = step(p, st, sst, jnp.asarray(jnp.inf))
    assert int(st.step) == 1  # skipped
    assert float(sst.loss_scale) == 2.0 ** 15
    p, st, sst, _ = step(p, st, sst, jnp.asarray(1.0))
    assert int(st.step) == 2


def test_lamb_grad_scale_matches_unscale_then_step():
    """step(grad_scale=S) on S-scaled grads == unscale-then-step (the
    fused amp tail): trajectories identical, overflow detected from the
    norm."""
    from apex_tpu.optimizers import FusedLAMB

    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(33, 17).astype("f4")),
              "b": jnp.asarray(rng.randn(17).astype("f4"))}
    grads = {"w": jnp.asarray(rng.randn(33, 17).astype("f4") * 0.1),
             "b": jnp.asarray(rng.randn(17).astype("f4") * 0.1)}
    scale = 2.0 ** 12
    scaled = jax.tree.map(lambda g: g * scale, grads)
    opt = FusedLAMB(lr=1e-2, weight_decay=0.01)

    p_ref, s_ref = params, opt.init(params)
    p_fus, s_fus = params, opt.init(params)
    for _ in range(3):
        p_ref, s_ref = opt.step(grads, s_ref, p_ref)
        p_fus, s_fus, found = opt.step(scaled, s_fus, p_fus,
                                       grad_scale=scale)
        assert not bool(found)
    for a, b in zip(jax.tree.leaves(p_ref), jax.tree.leaves(p_fus)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)

    # overflow: inf in scaled grads -> found, step skipped entirely
    bad = jax.tree.map(lambda g: g.at[0].set(jnp.inf)
                       if g.ndim == 1 else g, scaled)
    p3, s3, found = opt.step(bad, s_fus, p_fus, grad_scale=scale)
    assert bool(found)
    for a, b in zip(jax.tree.leaves(p3), jax.tree.leaves(p_fus)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(s3.step) == int(s_fus.step)


def test_scaled_value_and_grad_defers_unscale():
    """handle/scaler scaled_value_and_grad returns SCALED grads equal to
    scale * value_and_grad's unscaled grads, and the same loss."""
    from apex_tpu.amp import LossScaler

    scaler = LossScaler()
    st = scaler.init()
    w = jnp.asarray(np.random.RandomState(0).randn(8, 4).astype("f4"))

    def loss_fn(w):
        return jnp.mean(w ** 2)

    (loss_a, found), g_unscaled = scaler.value_and_grad(loss_fn, st)(w)
    loss_b, g_scaled = scaler.scaled_value_and_grad(loss_fn, st)(w)
    assert float(loss_a) == float(loss_b)
    np.testing.assert_allclose(
        np.asarray(g_scaled),
        np.asarray(g_unscaled) * float(st.loss_scale), rtol=1e-6)


# ---------------------------------------------------------------------------
# round 5: bf16-moments LAMB (opt-in low-HBM optimizer tier)
# ---------------------------------------------------------------------------

def test_lamb_bf16_moments_tracks_fp32_lamb():
    """One step from zero moments: the bf16-moments path must match the
    fp32 reference path to bf16-rounding tolerance (same clip, trust
    ratio, decoupled wd)."""
    from apex_tpu.optimizers import FusedLAMB

    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(64, 64).astype("f4") * 0.1),
              "b": jnp.asarray(rng.randn(64).astype("f4"))}
    grads = jax.tree.map(lambda p: p * 0.03 + 0.01, params)

    f32_opt = FusedLAMB(lr=1e-2)
    bf_opt = FusedLAMB(lr=1e-2, moments_dtype="bfloat16",
                       stochastic_rounding=False)
    p_ref, s_ref = f32_opt.step(grads, f32_opt.init(params), params)
    p_bf, s_bf = bf_opt.step(grads, bf_opt.init(params), params)

    assert jax.tree.leaves(s_bf.exp_avg)[0].dtype == jnp.bfloat16
    for a, b in zip(jax.tree.leaves(p_bf), jax.tree.leaves(p_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-3, rtol=2e-2)


@pytest.mark.slow
def test_lamb_bf16_moments_stochastic_rounding_keeps_ema_alive():
    """The reason SR exists: a (1-beta2)*g^2 increment far below the
    current v rounds-to-nearest to ZERO in bf16 and v stalls; with SR
    the EMA keeps moving in expectation. Run 300 steps of constant
    small grad against a big initial v and compare drift."""
    from apex_tpu.optimizers import FusedLAMB

    params = {"w": jnp.ones((64, 64), jnp.float32)}
    g = {"w": jnp.full((64, 64), 1e-3, jnp.float32)}

    def drift(sr):
        opt = FusedLAMB(lr=0.0, weight_decay=0.0, max_grad_norm=0.0,
                        moments_dtype="bfloat16", stochastic_rounding=sr,
                        bias_correction=False)
        st = opt.init(params)
        # big v: increments (1-b2)*g^2 = 1e-9 vs v=1.0 are far below
        # bf16 resolution (~2^-8)
        st = st._replace(
            exp_avg_sq=jax.tree.map(lambda x: jnp.ones_like(x), st.exp_avg_sq))

        @jax.jit
        def many(p, s):
            for _ in range(30):
                p, s = opt.step(g, s, p)
            return p, s

        p = params
        for _ in range(10):
            p, st = many(p, st)
        # with b2=0.999 over 300 steps from v=1.0 toward g^2~=1e-6,
        # exact EMA decays v to ~0.74
        return float(jnp.mean(jnp.asarray(st.exp_avg_sq["w"],
                                          jnp.float32)))

    v_rne = drift(sr=False)
    v_sr = drift(sr=True)
    assert v_rne == 1.0, f"RNE arm should stall exactly, got {v_rne}"
    assert 0.6 < v_sr < 0.9, (
        f"SR arm should decay toward the exact EMA (~0.74), got {v_sr}")


def test_lamb_bf16_moments_grad_scale_and_skip():
    """The amp fused tail (grad_scale) and the overflow skip contract
    hold on the bf16-moments path."""
    from apex_tpu.optimizers import FusedLAMB

    params = {"w": jnp.ones((8, 8), jnp.float32)}
    grads = {"w": jnp.full((8, 8), 64.0, jnp.float32)}  # scaled by 64
    opt = FusedLAMB(lr=1e-2, moments_dtype="bfloat16")
    st = opt.init(params)
    p2, st2, found = opt.step(grads, st, params, grad_scale=64.0)
    assert not bool(found)
    assert int(st2.step) == 1
    assert not np.array_equal(np.asarray(p2["w"]), np.asarray(params["w"]))

    bad = {"w": grads["w"].at[0, 0].set(jnp.inf)}
    p3, st3, found3 = opt.step(bad, st, params, grad_scale=64.0)
    assert bool(found3)
    np.testing.assert_array_equal(np.asarray(p3["w"]),
                                  np.asarray(params["w"]))
    assert int(st3.step) == 0


def test_stochastic_round_is_unbiased_and_exact_on_representable():
    from apex_tpu.ops.multi_tensor import stochastic_round

    key = jax.random.PRNGKey(0)
    # representable values round exactly regardless of bits
    x = jnp.asarray([1.0, -2.5, 0.0, 384.0], jnp.float32)
    np.testing.assert_array_equal(
        np.asarray(stochastic_round(x, jnp.bfloat16, key), np.float32),
        np.asarray(x))
    # non-finite passes through
    bad = jnp.asarray([jnp.inf, -jnp.inf, jnp.nan], jnp.float32)
    out = np.asarray(stochastic_round(bad, jnp.bfloat16, key), np.float32)
    assert np.isinf(out[0]) and np.isinf(out[1]) and np.isnan(out[2])
    # unbiased: mean of many rounds of a midpoint value ~= the value
    mid = jnp.full((20000,), 1.0 + 2.0 ** -9, jnp.float32)  # halfway ULP
    r = stochastic_round(mid, jnp.bfloat16, key).astype(jnp.float32)
    assert abs(float(jnp.mean(r)) - (1.0 + 2.0 ** -9)) < 2e-4
    # and it actually dithers (both neighbors appear)
    assert len(np.unique(np.asarray(r))) == 2


def test_stochastic_round_never_overflows_finite_values_to_inf():
    """Regression (advisor r5 #1): the mantissa-dither add can carry into
    the exponent, so finite fp32 values in the last bf16 ULP below
    bf16-max — or between bf16-max and fp32-max — must saturate at the
    finite bf16 max, never round to inf (an inf in exp_avg_sq is sticky
    and permanently kills that parameter's updates)."""
    from apex_tpu.ops.multi_tensor import stochastic_round

    bf16_max = float(jnp.finfo(jnp.bfloat16).max)
    fp32_max = float(np.finfo(np.float32).max)
    last_ulp = float(np.nextafter(np.float32(bf16_max), np.float32(0)))
    boundary = jnp.asarray(
        [bf16_max, -bf16_max, last_ulp, -last_ulp,
         3.4e38, -3.4e38, fp32_max, -fp32_max],   # 3.4e38: finite fp32
        jnp.float32)                              # strictly above bf16-max
    # many keys: the overflow only fires for dither bits that carry
    for seed in range(32):
        out = np.asarray(
            stochastic_round(boundary, jnp.bfloat16,
                             jax.random.PRNGKey(seed)), np.float32)
        assert np.isfinite(out).all(), (seed, out)
        assert (np.abs(out) <= bf16_max).all(), (seed, out)
    # true non-finite inputs still pass through untouched
    inf = jnp.asarray([np.inf, -np.inf], jnp.float32)
    out = np.asarray(stochastic_round(inf, jnp.bfloat16,
                                      jax.random.PRNGKey(0)), np.float32)
    assert np.isinf(out).all()


def test_adam_bf16_moments_tracks_fp32_adam():
    """FusedAdam's bf16-moments tier: one step from zero moments must
    match the fp32 path to rounding tolerance, and the stored moments
    must actually be bf16."""
    rng = np.random.RandomState(7)
    params = {"w": jnp.asarray(rng.randn(32, 32).astype("f4") * 0.1)}
    grads = jax.tree.map(lambda p: p * 0.05 + 0.02, params)

    ref = FusedAdam(lr=1e-2, weight_decay=0.01)
    bf = FusedAdam(lr=1e-2, weight_decay=0.01,
                   moments_dtype="bfloat16", stochastic_rounding=False)
    p_ref, _ = ref.step(grads, ref.init(params), params)
    p_bf, s_bf = bf.step(grads, bf.init(params), params)
    assert s_bf.exp_avg["w"].dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(p_bf["w"]),
                               np.asarray(p_ref["w"]),
                               atol=2e-3, rtol=2e-2)
    with pytest.raises(ValueError):
        FusedAdam(moments_dtype="float16")


def test_adam_bf16_moments_sr_keeps_ema_alive():
    """Same stall physics as the LAMB test, via the shared
    multi_tensor_adam sr_key path (short version)."""
    params = {"w": jnp.ones((32, 32), jnp.float32)}
    g = {"w": jnp.full((32, 32), 1e-3, jnp.float32)}

    def drift(sr):
        opt = FusedAdam(lr=0.0, moments_dtype="bfloat16",
                        stochastic_rounding=sr, bias_correction=False)
        st = opt.init(params)
        st = st._replace(exp_avg_sq=jax.tree.map(jnp.ones_like,
                                                 st.exp_avg_sq))

        @jax.jit
        def many(p, s):
            for _ in range(40):
                p, s = opt.step(g, s, p)
            return p, s

        p = params
        for _ in range(5):
            p, st = many(p, st)
        return float(jnp.mean(jnp.asarray(st.exp_avg_sq["w"], jnp.float32)))

    assert drift(False) == 1.0          # RNE stalls exactly
    assert drift(True) < 0.95           # SR decays toward the true EMA
