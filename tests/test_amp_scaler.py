"""Loss-scaler contract tests.

Pins the reference constants (SURVEY.md §3.2): init 2**16, backoff /2 on
overflow, growth x2 every 2000 clean steps, max 2**24 — the behaviors
upstream ``tests/L0/run_amp`` greps for.
"""

import jax
import jax.numpy as jnp
import pytest

from apex_tpu.amp import LossScaler


def test_init_scale_default():
    scaler = LossScaler()
    st = scaler.init()
    assert float(st.loss_scale) == 2.0 ** 16


def test_static_scale_never_changes():
    scaler = LossScaler(loss_scale=128.0)
    st = scaler.init()
    assert float(st.loss_scale) == 128.0
    st = scaler.update(st, jnp.asarray(True))
    assert float(st.loss_scale) == 128.0
    assert int(st.steps_skipped) == 1


def test_backoff_on_overflow():
    scaler = LossScaler()
    st = scaler.init()
    st = scaler.update(st, jnp.asarray(True))
    assert float(st.loss_scale) == 2.0 ** 15
    assert int(st.unskipped) == 0
    assert int(st.steps_skipped) == 1


def test_growth_after_interval():
    scaler = LossScaler(scale_seq_len=4)  # shrink the 2000-step window
    st = scaler.init()
    for _ in range(3):
        st = scaler.update(st, jnp.asarray(False))
        assert float(st.loss_scale) == 2.0 ** 16
    st = scaler.update(st, jnp.asarray(False))
    assert float(st.loss_scale) == 2.0 ** 17
    assert int(st.unskipped) == 0


def test_growth_capped_at_max():
    scaler = LossScaler(scale_seq_len=1, max_loss_scale=2.0 ** 17)
    st = scaler.init()
    for _ in range(5):
        st = scaler.update(st, jnp.asarray(False))
    assert float(st.loss_scale) == 2.0 ** 17


def test_no_floor_by_default_backs_off_below_one():
    """Reference default min_loss_scale=None: scale may go below 1.0, which
    is how training recovers when grads overflow even at scale 1."""
    scaler = LossScaler()
    st = scaler.init()._replace(loss_scale=jnp.asarray(1.0, jnp.float32))
    st = scaler.update(st, jnp.asarray(True))
    assert float(st.loss_scale) == 0.5


def test_backoff_floored_at_min():
    scaler = LossScaler(min_loss_scale=2.0 ** 15)
    st = scaler.init()
    for _ in range(5):
        st = scaler.update(st, jnp.asarray(True))
    assert float(st.loss_scale) == 2.0 ** 15


def test_unscale_detects_inf_and_nan():
    scaler = LossScaler()
    st = scaler.init()
    good = {"w": jnp.ones((4,)) * st.loss_scale}
    grads, found = scaler.unscale(good, st)
    assert not bool(found)
    assert jnp.allclose(grads["w"], 1.0)

    bad = {"w": jnp.array([1.0, jnp.inf, 3.0, 4.0])}
    _, found = scaler.unscale(bad, st)
    assert bool(found)

    nan = {"w": jnp.array([1.0, jnp.nan, 3.0, 4.0])}
    _, found = scaler.unscale(nan, st)
    assert bool(found)


def test_value_and_grad_scales_and_unscales():
    scaler = LossScaler(loss_scale=1024.0)
    st = scaler.init()

    def loss_fn(p):
        return jnp.sum(p ** 2)

    p = jnp.arange(4.0)
    (loss, found), grads = scaler.value_and_grad(loss_fn, st)(p)
    assert not bool(found)
    # Reported loss is unscaled; grads are unscaled.
    assert jnp.allclose(loss, jnp.sum(p ** 2))
    assert jnp.allclose(grads, 2 * p)


def test_step_skip_via_maybe_apply():
    scaler = LossScaler()
    st = scaler.init()
    old = {"w": jnp.zeros((3,))}
    new = {"w": jnp.ones((3,))}
    # overflow -> keep old params, scale halves
    tree, st2 = scaler.maybe_apply(st, jnp.asarray(True), new, old)
    assert jnp.allclose(tree["w"], 0.0)
    assert float(st2.loss_scale) == 2.0 ** 15
    # clean -> take new params
    tree, st3 = scaler.maybe_apply(st2, jnp.asarray(False), new, old)
    assert jnp.allclose(tree["w"], 1.0)


def test_whole_step_is_jittable():
    """The scaler must live happily inside one jit (no host sync)."""
    scaler = LossScaler()

    @jax.jit
    def step(p, st, x):
        def loss_fn(p):
            return jnp.sum((p * x) ** 2)

        (loss, found), grads = scaler.value_and_grad(loss_fn, st)(p)
        newp = jax.tree.map(lambda a, g: a - 0.1 * g, p, grads)
        p2, st2 = scaler.maybe_apply(st, found, newp, p)
        return p2, st2, loss

    p = jnp.ones((4,))
    st = scaler.init()
    p, st, loss = step(p, st, jnp.ones((4,)))
    assert int(st.steps_skipped) == 0
    # inject an overflow through the input
    p_bad, st, _ = step(p, st, jnp.array([jnp.inf, 1.0, 1.0, 1.0]))
    assert int(st.steps_skipped) == 1
    assert float(st.loss_scale) == 2.0 ** 15
    assert jnp.allclose(p_bad, p)  # step skipped


def test_mnist_style_smoke_recovers_from_overflow():
    """BASELINE configs[0]: 2-layer MLP, scaler backs off on an injected inf
    then resumes training and the loss decreases."""
    import numpy as np

    rng = np.random.RandomState(0)
    X = jnp.asarray(rng.randn(64, 16).astype("float32"))
    Y = jnp.asarray((rng.randn(64) > 0).astype("int32"))

    params = {
        "w1": jnp.asarray(rng.randn(16, 32).astype("float32") * 0.1),
        "b1": jnp.zeros((32,)),
        "w2": jnp.asarray(rng.randn(32, 2).astype("float32") * 0.1),
        "b2": jnp.zeros((2,)),
    }
    scaler = LossScaler()
    st = scaler.init()

    def loss_fn(p, scale_bomb):
        h = jnp.tanh(X @ p["w1"] + p["b1"])
        logits = h @ p["w2"] + p["b2"]
        logp = jax.nn.log_softmax(logits)
        loss = -jnp.mean(logp[jnp.arange(64), Y])
        return loss * scale_bomb  # scale_bomb=inf injects an overflow

    @jax.jit
    def step(p, st, bomb):
        (loss, found), grads = scaler.value_and_grad(lambda q: loss_fn(q, bomb), st)(p)
        newp = jax.tree.map(lambda a, g: a - 0.5 * g, p, grads)
        p2, st2 = scaler.maybe_apply(st, found, newp, p)
        return p2, st2, loss

    losses = []
    for i in range(30):
        bomb = jnp.asarray(jnp.inf if i == 5 else 1.0, jnp.float32)
        params, st, loss = step(params, st, bomb)
        if i != 5:
            losses.append(float(loss))
    assert int(st.steps_skipped) == 1
    assert float(st.loss_scale) == 2.0 ** 15
    assert losses[-1] < losses[0]


def test_hysteresis_delays_backoff():
    """hysteresis=N: the scale holds through N-1 consecutive overflows
    (each step still skipped) and backs off on the Nth
    (amp_C.update_scale_hysteresis semantics)."""
    scaler = LossScaler(hysteresis=3)
    st = scaler.init()
    t = jnp.asarray(True)
    st = scaler.update(st, t)
    st = scaler.update(st, t)
    assert float(st.loss_scale) == 2.0 ** 16  # tolerance not yet used up
    assert int(st.steps_skipped) == 2         # but both steps skipped
    st = scaler.update(st, t)
    assert float(st.loss_scale) == 2.0 ** 15  # third overflow backs off
    # tolerance does NOT replenish on back-off (reference tracker
    # semantics): while the streak continues every overflow backs off,
    # so recovery from a far-too-high initial scale is not slowed
    st = scaler.update(st, t)
    assert float(st.loss_scale) == 2.0 ** 14


def test_hysteresis_replenishes_on_every_clean_step():
    """The reference kernel (amp_C.update_scale_hysteresis) refills the
    tracker to its full value on EVERY non-overflow step, so only
    *consecutive* overflows deplete it — spiky losses whose overflows
    are separated by clean steps must never back the scale off."""
    scaler = LossScaler(hysteresis=2, scale_seq_len=2000)
    st = scaler.init()
    st = scaler.update(st, jnp.asarray(True))   # tolerance 2 -> 1
    assert int(st.hysteresis) == 1
    st = scaler.update(st, jnp.asarray(False))  # clean: refilled to 2
    assert int(st.hysteresis) == 2
    # alternating overflow/clean forever: the scale holds
    for _ in range(4):
        st = scaler.update(st, jnp.asarray(True))
        st = scaler.update(st, jnp.asarray(False))
    assert float(st.loss_scale) == 2.0 ** 16
    assert int(st.steps_skipped) == 5


def test_default_hysteresis_matches_reference_backoff():
    """hysteresis=1 (default) must reproduce the core-amp contract
    exactly: every overflow halves the scale."""
    st = LossScaler().init()
    st = LossScaler().update(st, jnp.asarray(True))
    assert float(st.loss_scale) == 2.0 ** 15
