"""Fused softmax kernel tests (upstream analog:
tests/L0/run_transformer/test_fused_softmax.py, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.softmax import (
    scaled_masked_softmax,
    scaled_softmax,
    scaled_upper_triang_masked_softmax,
    softmax_reference,
)
from apex_tpu.transformer.functional import AttnMaskType, FusedScaleMaskSoftmax


def _x(shape, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(*shape).astype("float32")).astype(dtype)


@pytest.mark.parametrize("shape", [(2, 4, 8, 128), (2, 2, 16, 100), (1, 1, 8, 256)])
def test_scaled_softmax_matches_reference(shape):
    x = _x(shape)
    y = scaled_softmax(x, 0.5)
    ref = softmax_reference(x, scale=0.5)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-6)


def test_scaled_masked_softmax_bool_mask():
    x = _x((2, 4, 8, 64))
    rng = np.random.RandomState(1)
    mask = jnp.asarray(rng.rand(2, 1, 8, 64) > 0.7)
    y = scaled_masked_softmax(x, mask, 2.0)
    ref = softmax_reference(x, jnp.broadcast_to(mask, x.shape), 2.0)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-6)
    # masked positions ~ 0 probability
    got = np.asarray(y)
    assert got[np.broadcast_to(np.asarray(mask), got.shape)].max() < 1e-6


def test_scaled_masked_softmax_additive_mask():
    x = _x((2, 2, 4, 32))
    mask = jnp.where(_x((2, 1, 4, 32), 3) > 0, 0.0, -1e9).astype(jnp.float32)
    y = scaled_masked_softmax(x, mask, 1.0)
    ref = softmax_reference(x, jnp.broadcast_to(mask, x.shape), 1.0)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("sq", [8, 64, 100])
def test_causal_softmax(sq):
    x = _x((2, 2, sq, sq))
    y = scaled_upper_triang_masked_softmax(x, 1.0)
    ref = softmax_reference(x, causal=True)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-6)
    got = np.asarray(y)
    # strictly upper triangle must be ~0
    iu = np.triu_indices(sq, 1)
    assert got[..., iu[0], iu[1]].max() < 1e-6
    # rows sum to 1
    np.testing.assert_allclose(got.sum(-1), 1.0, rtol=1e-5)


def test_causal_requires_square():
    with pytest.raises(ValueError):
        scaled_upper_triang_masked_softmax(_x((2, 2, 8, 16)))


def test_softmax_grads_match_reference():
    x = _x((2, 2, 8, 64))

    def fused_loss(x):
        return jnp.sum(jnp.sin(scaled_softmax(x, 1.7)))

    def ref_loss(x):
        return jnp.sum(jnp.sin(softmax_reference(x, scale=1.7)))

    gf = jax.grad(fused_loss)(x)
    gr = jax.grad(ref_loss)(x)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gr), rtol=1e-4, atol=1e-5)


def test_causal_grads_match_reference():
    x = _x((1, 2, 32, 32))
    gf = jax.grad(lambda x: jnp.sum(jnp.sin(scaled_upper_triang_masked_softmax(x, 0.8))))(x)
    gr = jax.grad(lambda x: jnp.sum(jnp.sin(softmax_reference(x, scale=0.8, causal=True))))(x)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gr), rtol=1e-4, atol=1e-5)


def test_bf16_io():
    x = _x((2, 2, 8, 128), dtype=jnp.bfloat16)
    y = scaled_softmax(x, 1.0)
    assert y.dtype == jnp.bfloat16
    ref = softmax_reference(x)
    np.testing.assert_allclose(np.asarray(y, np.float32), np.asarray(ref, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_module_dispatch():
    sm = FusedScaleMaskSoftmax(attn_mask_type=AttnMaskType.causal, scale=0.5)
    x = _x((2, 4, 16, 16))
    y = sm(x)
    ref = softmax_reference(x, scale=0.5, causal=True)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5, atol=1e-6)

    sm2 = FusedScaleMaskSoftmax(scaled_masked_softmax_fusion=False)
    y2 = sm2(x, None)
    np.testing.assert_allclose(np.asarray(y2), np.asarray(softmax_reference(x)),
                               rtol=1e-5, atol=1e-6)


def test_module_validation():
    with pytest.raises(RuntimeError):
        FusedScaleMaskSoftmax(input_in_fp16=True, input_in_bf16=True)
    with pytest.raises(RuntimeError):
        FusedScaleMaskSoftmax(softmax_in_fp32=False, scale=2.0)


def test_causal_with_padding_mask_matches_fallback():
    """Review regression: the fused causal path must honor a padding mask
    identically to the non-fused fallback."""
    x = _x((2, 2, 16, 16))
    mask = jnp.zeros((2, 1, 16, 16), bool).at[..., -3:].set(True)
    fused = FusedScaleMaskSoftmax(attn_mask_type=AttnMaskType.causal, scale=0.5)
    slow = FusedScaleMaskSoftmax(attn_mask_type=AttnMaskType.causal, scale=0.5,
                                 scaled_masked_softmax_fusion=False)
    yf = np.asarray(fused(x, mask))
    ys = np.asarray(slow(x, jnp.broadcast_to(mask, x.shape)))
    # padded keys get ~zero probability on both paths
    assert yf[np.broadcast_to(np.asarray(mask), yf.shape)].max() < 1e-6
    np.testing.assert_allclose(yf, ys, rtol=1e-4, atol=1e-5)


def test_module_handles_2d_and_5d_inputs():
    sm = FusedScaleMaskSoftmax()
    y2 = sm(_x((8, 32)))
    np.testing.assert_allclose(np.asarray(y2.sum(-1)), 1.0, rtol=1e-5)
    y5 = sm(_x((2, 2, 3, 4, 32)))
    np.testing.assert_allclose(np.asarray(y5.sum(-1)), 1.0, rtol=1e-5)


def test_transformer_enums_surface():
    """apex.transformer.enums parity: AttnMaskType re-exported next to
    the softmax it configures; structural selectors present."""
    from apex_tpu.transformer.enums import (
        AttnMaskType,
        AttnType,
        LayerType,
        ModelType,
    )
    from apex_tpu.transformer.functional import (
        AttnMaskType as FunctionalAttnMaskType,
    )

    assert AttnMaskType is FunctionalAttnMaskType
    assert {m.name for m in ModelType} == {"encoder_or_decoder",
                                           "encoder_and_decoder"}
    assert {m.name for m in LayerType} == {"encoder", "decoder"}
    assert {m.name for m in AttnType} == {"self_attn", "cross_attn"}


@pytest.mark.parametrize("scale", [-2.0, -0.5, 0.0, 1e-6, 1e3])
def test_scaled_masked_softmax_any_scale_bool(scale):
    """In-kernel mask application (after the scale multiply, the
    reference's order) makes every scale valid — negative scales must
    still mask, not un-mask (the round-1 sign-flip hazard)."""
    x = _x((2, 2, 8, 64))
    rng = np.random.RandomState(5)
    mask = jnp.asarray(rng.rand(2, 1, 8, 64) > 0.6)
    y = scaled_masked_softmax(x, mask, scale)
    ref = softmax_reference(x, jnp.broadcast_to(mask, x.shape), scale)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
    got = np.asarray(y)
    assert got[np.broadcast_to(np.asarray(mask), got.shape)].max() < 1e-6


def test_scaled_masked_softmax_tiny_scale_fp16():
    """fp16 x with a scale small enough that the old fill/scale pre-fold
    would clamp at the dtype min and under-mask; the in-kernel path must
    stay exact."""
    x = _x((2, 1, 8, 32)).astype(jnp.float16)
    rng = np.random.RandomState(7)
    mask = jnp.asarray(rng.rand(2, 1, 8, 32) > 0.5)
    y = scaled_masked_softmax(x, mask, 0.01)
    got = np.asarray(y, np.float32)
    assert got[np.broadcast_to(np.asarray(mask), got.shape)].max() < 1e-6
    ref = softmax_reference(x.astype(jnp.float32),
                            jnp.broadcast_to(mask, x.shape), 0.01)
    np.testing.assert_allclose(got, np.asarray(ref), rtol=1e-2, atol=1e-3)


@pytest.mark.parametrize("scale", [1.0, -1.0])
def test_scaled_masked_softmax_additive_negative_scale(scale):
    x = _x((2, 2, 4, 32))
    mask = jnp.where(_x((2, 1, 4, 32), 3) > 0, 0.0, -1e9).astype(jnp.float32)
    y = scaled_masked_softmax(x, mask, scale)
    ref = softmax_reference(x, jnp.broadcast_to(mask, x.shape), scale)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_scaled_masked_softmax_causal_combined():
    """causal=True + padding mask, incl. a negative scale (the
    FusedScaleMaskSoftmax causal-with-mask route)."""
    for scale in (0.7, -0.7):
        x = _x((2, 2, 16, 16))
        rng = np.random.RandomState(9)
        mask = jnp.asarray(rng.rand(2, 1, 1, 16) > 0.7)
        y = scaled_masked_softmax(x, mask, scale, causal=True)
        ref = softmax_reference(x, jnp.broadcast_to(mask, x.shape), scale,
                                causal=True)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)


def test_additive_mask_receives_gradient():
    """A learned additive bias (ALiBi/relative-position style) fed as the
    float mask must get the softmax-backward cotangent, matching
    autodiff of the composed reference (regression: the in-kernel route
    must not orphan the mask input)."""
    x = _x((2, 2, 4, 32))
    w = _x((2, 2, 4, 32), 11)
    bias = jnp.zeros((2, 1, 1, 32), jnp.float32)

    for scale in (1.0, -1.0):
        def loss(b):
            return jnp.sum(scaled_masked_softmax(x, b, scale) * w)

        def loss_ref(b):
            return jnp.sum(softmax_reference(
                x, jnp.broadcast_to(b, x.shape), scale) * w)

        g = jax.grad(loss)(bias)
        gr = jax.grad(loss_ref)(bias)
        assert float(jnp.abs(g).max()) > 0
        np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                                   rtol=1e-4, atol=1e-6)
