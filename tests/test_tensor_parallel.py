"""Tensor-parallel tests (upstream analog: tests/L0/run_transformer/
{test_parallel_state,test_layers,test_cross_entropy,test_random}.py,
SURVEY.md §4), on the 8-device CPU mesh with tp=4, dp=2."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    copy_to_tensor_model_parallel_region,
    gather_along_first_dim,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_along_first_dim,
    vocab_parallel_cross_entropy,
)


@pytest.fixture(autouse=True)
def _mp(request):
    parallel_state.initialize_model_parallel(tensor_model_parallel_size_=4)
    yield
    parallel_state.destroy_model_parallel()


def _tp_map(f, *args, in_specs=None, out_specs=P()):
    """Run f in shard_map over the full (pp=1, dp=2, tp=4) mesh."""
    mesh = parallel_state.get_mesh()
    return jax.jit(
        jax.shard_map(f, mesh=mesh,
                      in_specs=in_specs if in_specs is not None else P(),
                      out_specs=out_specs)
    )(*args)


def test_parallel_state_sizes():
    assert parallel_state.get_tensor_model_parallel_world_size() == 4
    assert parallel_state.get_data_parallel_world_size() == 2
    assert parallel_state.get_pipeline_model_parallel_world_size() == 1
    assert parallel_state.model_parallel_is_initialized()
    mesh = parallel_state.get_mesh()
    assert mesh.shape == {"pipeline": 1, "data": 2, "expert": 1,
                          "tensor": 4}


def test_parallel_state_validation():
    parallel_state.destroy_model_parallel()
    with pytest.raises(RuntimeError):
        parallel_state.initialize_model_parallel(tensor_model_parallel_size_=3)
    with pytest.raises(RuntimeError):
        parallel_state.get_mesh()


def test_mappings_roundtrip_and_grads():
    x = jnp.asarray(np.random.RandomState(0).randn(6, 8).astype("float32"))

    def f(x):
        # scatter -> gather must be identity
        y = gather_from_tensor_model_parallel_region(
            jax.lax.dynamic_slice_in_dim(
                x, jax.lax.axis_index("tensor") * 2, 2, axis=1)
        )
        # copy fwd is identity
        z = copy_to_tensor_model_parallel_region(x)
        # reduce of rank-constant input = tp * x
        r = reduce_from_tensor_model_parallel_region(jax.lax.pcast(x, "tensor", to="varying"))
        # pmean marks the gathered (identical) values vma-invariant for P()
        return jax.lax.pmean(y, "tensor"), jax.lax.pmean(z, "tensor"), r

    y, z, r = _tp_map(f, x, out_specs=(P(), P(), P()))
    np.testing.assert_allclose(np.asarray(y), np.asarray(x), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(z), np.asarray(x), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(r), 4 * np.asarray(x), rtol=1e-6)


def test_copy_region_grad_is_psum():
    x = jnp.ones((4,))

    def f(x):
        def loss(q):
            q = copy_to_tensor_model_parallel_region(q)
            # per-rank different scaling => grad must sum the branches
            scale = (jax.lax.axis_index("tensor") + 1).astype(jnp.float32)
            return jnp.sum(q * scale)

        return jax.grad(loss)(x)

    g = _tp_map(f, x)
    # psum over ranks of scale = 1+2+3+4 = 10... but shard_map AD already
    # sums replicated-input grads; the mapping's explicit psum must not
    # double-count. Expected grad: d/dx sum over ranks (x*scale) = 10.
    np.testing.assert_allclose(np.asarray(g), 10.0 * np.ones(4), rtol=1e-5)


def test_sp_first_dim_pair_roundtrip():
    x = jnp.asarray(np.random.RandomState(1).randn(8, 4).astype("float32"))

    def f(x):
        full = gather_along_first_dim(x)          # (32, 4) per rank? no:
        back = reduce_scatter_along_first_dim(full)
        return back

    # feed per-rank shards via the tensor axis
    mesh = parallel_state.get_mesh()
    big = jnp.asarray(np.random.RandomState(1).randn(32, 4).astype("float32"))
    out = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=P("tensor"), out_specs=P("tensor"))
    )(big)
    # gather then reduce-scatter of a gathered value sums tp copies of each
    # shard: out = tp * x
    np.testing.assert_allclose(np.asarray(out), 4 * np.asarray(big), rtol=1e-5)


def test_column_parallel_linear_matches_dense():
    layer = ColumnParallelLinear(input_size=8, output_size=16, gather_output=True)
    x = jnp.asarray(np.random.RandomState(0).randn(4, 8).astype("float32"))

    def f(x):
        params = layer.init(jax.random.PRNGKey(7), x)
        y = layer.apply(params, x)
        kernel_full = jax.lax.all_gather(params["params"]["kernel"], "tensor",
                                         axis=1, tiled=True)
        bias_full = jax.lax.all_gather(params["params"]["bias"], "tensor",
                                       axis=0, tiled=True)
        return (jax.lax.pmean(y, "tensor"), jax.lax.pmean(kernel_full, "tensor"),
                jax.lax.pmean(bias_full, "tensor"))

    y, full_w, full_b = _tp_map(f, x, out_specs=(P(), P(), P()))
    assert full_w.shape == (8, 16)  # 4 ranks x (8, 4) concatenated
    # master-init slicing must decorrelate the shards
    w = np.asarray(full_w)
    assert not np.allclose(w[:, :4], w[:, 4:8])
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(x @ full_w + full_b),
                               rtol=1e-4, atol=1e-5)


def test_row_parallel_linear_matches_dense():
    col = ColumnParallelLinear(input_size=8, output_size=16, gather_output=False,
                               bias=False)
    row = RowParallelLinear(input_size=16, output_size=6, input_is_parallel=True,
                            bias=True)
    x = jnp.asarray(np.random.RandomState(0).randn(4, 8).astype("float32"))

    def f(x):
        pc = col.init(jax.random.PRNGKey(1), x)
        h = col.apply(pc, x)                       # local (4, 4) shard
        pr = row.init(jax.random.PRNGKey(2), h)
        y = row.apply(pr, h)
        wc = jax.lax.pmean(jax.lax.all_gather(
            pc["params"]["kernel"], "tensor", axis=1, tiled=True), "tensor")
        wr = jax.lax.pmean(jax.lax.all_gather(
            pr["params"]["kernel"], "tensor", axis=0, tiled=True), "tensor")
        return y, wc, wr

    y, wc, wr = _tp_map(f, x, out_specs=(P(), P(), P()))
    np.testing.assert_allclose(np.asarray(y), np.asarray(x @ wc @ wr),
                               rtol=1e-4, atol=1e-5)


def test_tp_mlp_grads_match_single_device():
    """The core correctness property: a TP Column->Row MLP trained inside
    shard_map computes the same loss/grads as its assembled single-device
    equivalent."""
    col = ColumnParallelLinear(input_size=8, output_size=16, gather_output=False,
                               bias=False)
    row = RowParallelLinear(input_size=16, output_size=8, input_is_parallel=True,
                            bias=False)
    x = jnp.asarray(np.random.RandomState(3).randn(4, 8).astype("float32"))

    def f(x):
        pc = col.init(jax.random.PRNGKey(1), x)["params"]["kernel"]
        pr = row.init(jax.random.PRNGKey(2), jnp.zeros((4, 4)))["params"]["kernel"]

        def loss(w):
            wc, wr = w
            h = col.apply({"params": {"kernel": wc}}, x)
            y = row.apply({"params": {"kernel": wr}}, h)
            return jnp.sum(jnp.sin(y))

        l, g = jax.value_and_grad(loss)((pc, pr))
        # grads are per-shard; gather (then pmean to mark invariant)
        gc = jax.lax.pmean(
            jax.lax.all_gather(g[0], "tensor", axis=1, tiled=True), "tensor")
        gr = jax.lax.pmean(
            jax.lax.all_gather(g[1], "tensor", axis=0, tiled=True), "tensor")
        wc = jax.lax.pmean(
            jax.lax.all_gather(pc, "tensor", axis=1, tiled=True), "tensor")
        wr = jax.lax.pmean(
            jax.lax.all_gather(pr, "tensor", axis=0, tiled=True), "tensor")
        return l, gc, gr, wc, wr

    loss_tp, gc, gr, wc, wr = _tp_map(f, x, out_specs=(P(), P(), P(), P(), P()))

    def ref_loss(w):
        wc, wr = w
        return jnp.sum(jnp.sin(x @ wc @ wr))

    l_ref, (gc_ref, gr_ref) = jax.value_and_grad(ref_loss)((wc, wr))
    np.testing.assert_allclose(float(loss_tp), float(l_ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gc), np.asarray(gc_ref), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gr), np.asarray(gr_ref), rtol=1e-4, atol=1e-5)


def test_vocab_parallel_embedding():
    emb = VocabParallelEmbedding(num_embeddings=16, embedding_dim=6)
    ids = jnp.asarray([[0, 3, 7, 15], [8, 4, 11, 2]])

    def f(ids):
        p = emb.init(jax.random.PRNGKey(5), ids)
        out = emb.apply(p, ids)
        table = jax.lax.pmean(
            jax.lax.all_gather(p["params"]["embedding"], "tensor",
                               axis=0, tiled=True), "tensor")
        return out, table

    out, table = _tp_map(f, ids, out_specs=(P(), P()))
    ref = np.asarray(table)[np.asarray(ids)]
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-6)


def test_vocab_parallel_cross_entropy_matches_dense():
    vocab, batch = 32, 6
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(batch, vocab).astype("float32"))
    targets = jnp.asarray(rng.randint(0, vocab, batch))

    def f(logits, targets):
        rank = jax.lax.axis_index("tensor")
        local = jax.lax.dynamic_slice_in_dim(logits, rank * 8, 8, axis=1)

        def loss_fn(l):
            return jnp.sum(vocab_parallel_cross_entropy(l, targets))

        l, g = jax.value_and_grad(loss_fn)(local)
        return l, jax.lax.pmean(
            jax.lax.all_gather(g, "tensor", axis=1, tiled=True), "tensor")

    loss_tp, grad_tp = _tp_map(f, logits, targets,
                               in_specs=(P(), P()), out_specs=(P(), P()))

    def ref(l):
        logp = jax.nn.log_softmax(l, axis=-1)
        return -jnp.sum(logp[jnp.arange(batch), targets])

    l_ref, g_ref = jax.value_and_grad(ref)(logits)
    np.testing.assert_allclose(float(loss_tp), float(l_ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grad_tp), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-5)


def test_vocab_parallel_cross_entropy_label_smoothing():
    vocab, batch = 32, 4
    rng = np.random.RandomState(1)
    logits = jnp.asarray(rng.randn(batch, vocab).astype("float32"))
    targets = jnp.asarray(rng.randint(0, vocab, batch))
    eps = 0.1

    def f(logits, targets):
        rank = jax.lax.axis_index("tensor")
        local = jax.lax.dynamic_slice_in_dim(logits, rank * 8, 8, axis=1)
        return jnp.sum(vocab_parallel_cross_entropy(local, targets, eps))

    loss_tp = _tp_map(f, logits, targets, in_specs=(P(), P()))

    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -logp[jnp.arange(batch), targets]
    smooth = -logp.mean(axis=-1)
    ref = jnp.sum((1 - eps) * nll + eps * smooth)
    np.testing.assert_allclose(float(loss_tp), float(ref), rtol=1e-5)


def test_master_weight_init_parity():
    """The assembled tp>1 weight must equal the single-device init from
    the same key (the reference's _initialize_affine_weight contract) —
    so fan-in-scaled initializers keep the correct stddev at any tp."""
    col = ColumnParallelLinear(input_size=8, output_size=16, gather_output=False,
                               bias=False)
    row = RowParallelLinear(input_size=16, output_size=8, input_is_parallel=True,
                            bias=False)
    emb = VocabParallelEmbedding(num_embeddings=16, embedding_dim=8)
    x8 = jnp.zeros((4, 8))
    x4 = jnp.zeros((4, 4))
    ids = jnp.zeros((2, 3), jnp.int32)

    def f(_):
        wc = jax.lax.all_gather(
            col.init(jax.random.PRNGKey(1), x8)["params"]["kernel"],
            "tensor", axis=1, tiled=True)
        wr = jax.lax.all_gather(
            row.init(jax.random.PRNGKey(2), x4)["params"]["kernel"],
            "tensor", axis=0, tiled=True)
        we = jax.lax.all_gather(
            emb.init(jax.random.PRNGKey(3), ids)["params"]["embedding"],
            "tensor", axis=0, tiled=True)
        return (jax.lax.pmean(wc, "tensor"), jax.lax.pmean(wr, "tensor"),
                jax.lax.pmean(we, "tensor"))

    wc, wr, we = _tp_map(f, jnp.zeros(()), out_specs=(P(), P(), P()))

    # reference: the SAME modules initialized at tp=1 (full weights)
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(tensor_model_parallel_size_=1)

    def ref(_):
        return (col.init(jax.random.PRNGKey(1), x8)["params"]["kernel"],
                row.init(jax.random.PRNGKey(2), jnp.zeros((4, 16)))["params"]["kernel"],
                emb.init(jax.random.PRNGKey(3), ids)["params"]["embedding"])

    mesh1 = parallel_state.get_mesh()
    wc_ref, wr_ref, we_ref = jax.jit(jax.shard_map(
        ref, mesh=mesh1, in_specs=P(), out_specs=(P(), P(), P())))(jnp.zeros(()))
    np.testing.assert_allclose(np.asarray(wc), np.asarray(wc_ref), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(wr), np.asarray(wr_ref), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(we), np.asarray(we_ref), rtol=1e-6)
    # row-parallel stddev must reflect the FULL fan_in (16), not 16/tp
    assert abs(float(jnp.std(wr)) - (1.0 / 16) ** 0.5) < 0.05


def test_rng_tracker_streams():
    from apex_tpu.transformer.tensor_parallel import (
        get_rng_state_tracker,
        model_parallel_rng_seed,
    )

    tracker = model_parallel_rng_seed(1234)
    k1 = tracker.fork()
    k2 = tracker.fork()
    assert not np.array_equal(np.asarray(k1), np.asarray(k2))
    with pytest.raises(RuntimeError):
        tracker.fork("nonexistent")
    with pytest.raises(RuntimeError):
        tracker.add("model-parallel-rng", 1)

    # replay: same seed -> same stream
    t2 = model_parallel_rng_seed(1234)
    np.testing.assert_array_equal(np.asarray(t2.fork()), np.asarray(k1))


def test_model_parallel_key_differs_per_rank():
    from apex_tpu.transformer.tensor_parallel import model_parallel_key

    def f(_):
        k = model_parallel_key(jax.random.PRNGKey(0))
        return jax.random.uniform(k, (1,))[None]

    mesh = parallel_state.get_mesh()
    out = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=P("tensor"), out_specs=P("tensor"))
    )(jnp.zeros((4,)))
    vals = np.asarray(out).ravel()
    assert len(set(np.round(vals, 6))) == 4  # all ranks differ


def test_checkpoint_recompute_matches():
    from apex_tpu.transformer.tensor_parallel import checkpoint

    x = jnp.asarray(np.random.RandomState(0).randn(8, 8).astype("float32"))

    def block(x):
        return jnp.sum(jnp.tanh(x @ x.T))

    g1 = jax.grad(lambda x: checkpoint(block, x))(x)
    g2 = jax.grad(block)(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-6)


def test_main_grad_fp32_accumulation_beats_bf16():
    """The fused_weight_gradient parity property: accumulating many small
    bf16 microbatch grads into fp32 main_grad keeps precision that pure
    bf16 accumulation loses."""
    from apex_tpu.transformer.tensor_parallel import (
        accumulate_main_grads,
        init_main_grads,
        reset_main_grads,
    )

    params = {"w": jnp.zeros((64,), jnp.bfloat16)}
    micro_grad = {"w": jnp.full((64,), 1e-3, jnp.bfloat16)}
    steps = 512

    main = init_main_grads(params)
    bf16_acc = jnp.zeros((64,), jnp.bfloat16)
    for _ in range(steps):
        main = accumulate_main_grads(main, micro_grad)
        bf16_acc = bf16_acc + micro_grad["w"]

    true_sum = steps * float(jnp.asarray(micro_grad["w"][0], jnp.float32))
    fp32_err = abs(float(main["w"][0]) - true_sum)
    bf16_err = abs(float(jnp.asarray(bf16_acc[0], jnp.float32)) - true_sum)
    assert fp32_err < 1e-3
    assert bf16_err > 10 * max(fp32_err, 1e-6)  # bf16 visibly degrades

    zeroed = reset_main_grads(main)
    assert float(jnp.max(jnp.abs(zeroed["w"]))) == 0.0
    assert zeroed["w"].dtype == jnp.float32


def test_vocab_utility_and_split_helpers():
    """API-parity tier for tensor_parallel.utils (reference:
    apex/transformer/tensor_parallel/utils.py (U))."""
    import pytest

    from apex_tpu.transformer.tensor_parallel import (
        VocabUtility,
        divide,
        ensure_divisibility,
        split_tensor_along_last_dim,
    )

    assert divide(12, 4) == 3
    with pytest.raises(ValueError):
        ensure_divisibility(10, 3)

    # ranges tile [0, vocab) exactly, in rank order
    vocab, tp = 128, 4
    ranges = [VocabUtility.vocab_range_from_global_vocab_size(vocab, r, tp)
              for r in range(tp)]
    assert ranges[0] == (0, 32) and ranges[-1] == (96, 128)
    for (a0, a1), (b0, b1) in zip(ranges, ranges[1:]):
        assert a1 == b0 and a1 - a0 == vocab // tp

    x = jnp.arange(24.0).reshape(2, 12)
    chunks = split_tensor_along_last_dim(x, 3)
    assert len(chunks) == 3 and chunks[1].shape == (2, 4)
    assert jnp.array_equal(jnp.concatenate(chunks, axis=-1), x)
    with pytest.raises(ValueError):
        split_tensor_along_last_dim(x, 5)


# ---------------------------------------------------- hybrid DCN mesh

def test_hybrid_mesh_two_slices_tp_stays_on_ici():
    """2 simulated slices on the 8 virtual devices, dcn-dp outermost:
    every TP pair must live inside ONE slice (TP rides ICI), and the
    outer half of the data axis must cross slices (grad allreduce rides
    DCN), per SURVEY §2.4."""
    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=2,
        dcn_data_parallel_size_=2, num_slices=2)
    assert parallel_state.get_num_slices() == 2
    assert parallel_state.get_dcn_data_parallel_world_size() == 2
    assert parallel_state.get_ici_data_parallel_world_size() == 2
    assert mesh.shape == {"pipeline": 1, "data": 4, "expert": 1,
                          "tensor": 2}
    world = 8
    devs = mesh.devices  # (pp, dp, ep, tp)

    def slice_of(d):
        return d.id * 2 // world  # matches the simulated partitioning

    # TP pairs: same slice
    for idp in range(4):
        pair = devs[0, idp, 0, :]
        assert slice_of(pair[0]) == slice_of(pair[1])
    # data axis: inner half (rows 0-1) slice 0, outer half (rows 2-3)
    # slice 1 — the DCN factor is the outer positions
    row_slices = [slice_of(devs[0, idp, 0, 0]) for idp in range(4)]
    assert row_slices == [0, 0, 1, 1]


def test_hybrid_mesh_dcn_pipeline_outermost():
    """dcn-pp=2: pipeline stages split across slices with ICI stages
    contiguous inside each slice."""
    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=4,
        dcn_pipeline_model_parallel_size_=2, num_slices=2)
    assert parallel_state.get_ici_pipeline_model_parallel_world_size() == 2
    devs = mesh.devices

    def slice_of(d):
        return d.id * 2 // 8

    stage_slices = [slice_of(devs[ipp, 0, 0, 0]) for ipp in range(4)]
    assert stage_slices == [0, 0, 1, 1]


def test_hybrid_mesh_validation():
    parallel_state.destroy_model_parallel()
    with pytest.raises(RuntimeError, match="slice count"):
        parallel_state.initialize_model_parallel(
            dcn_data_parallel_size_=2, num_slices=4)
    with pytest.raises(RuntimeError, match="divisible by their DCN"):
        parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=4,  # dp=2
            dcn_data_parallel_size_=3, num_slices=3)


def test_hybrid_mesh_ddp_step_runs():
    """A DDP-style psum gradient sync compiles and runs over the hybrid
    mesh — the 'data' axis spans both slices transparently."""
    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=2,
        dcn_data_parallel_size_=2, num_slices=2)

    def f(g):
        return jax.lax.pmean(g, "data")

    g = jnp.arange(8.0).reshape(4, 2)
    out = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=P("data", "tensor"),
        out_specs=P("data", "tensor")))(g)
    cols = np.asarray(out).reshape(4, 2)
    np.testing.assert_allclose(cols, np.tile(np.asarray(g).mean(0), (4, 1)))
