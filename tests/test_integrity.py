"""End-to-end data-integrity certification (tier-1, CPU): the ISSUE 14
layer (docs/robustness.md, "Data integrity").

The detection bar: under seeded ``"corrupt"`` fault plans covering
every checksum point — spill writes/reads, checkpoints, migration
records in and out, transported KV payloads — zero corrupted artifacts
are consumed undetected: corrupt spill entries are discarded and the
request is served by recompute TOKEN-IDENTICALLY, corrupt checkpoints
fail over via fresh re-injection with zero lost accepted requests,
corrupt migration imports are refused with the source keeping the
request. The perturbation bar: integrity machinery fully disabled
(``verify_artifacts=False``, no scrub, no cross-check) is bit-identical
to the pre-integrity engine and fleet — outputs, statuses, and the
full stats dict — and enabling checksums alone changes no served
token. Plus: the ``"corrupt"`` fault kind and its seeded perturbation
helpers, the checksum/seal primitives (JSON-wire stable), budgeted
background scrubbing, the fleet SDC determinism cross-check (a
compute-corrupted replica is detected and retired), the recorder/
trace_summary surface, and the ``tools/bench_diff.py`` artifact
comparer."""

import importlib.util
import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import GPTConfig, GPTLMHeadModel
from apex_tpu.observability import RECORDER_EVENT_KINDS, Observability
from apex_tpu.serving import (
    EngineConfig,
    FleetConfig,
    FleetRouter,
    HostSpillStore,
    InferenceEngine,
    Request,
    SamplingParams,
)
from apex_tpu.utils.faults import (
    FaultPlan,
    FaultSpec,
    corruption_seed,
    perturb_json,
    perturb_payload,
    perturb_tokens,
)
from apex_tpu.utils.integrity import (
    IntegrityError,
    is_sealed,
    payload_checksum,
    record_checksum,
    seal_record,
    verify_payload,
    verify_record,
)


@pytest.fixture(scope="module")
def tiny_gpt():
    cfg = GPTConfig.tiny(dropout=0.0, remat=False)
    model = GPTLMHeadModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    return model, params


ENGINE_KW = dict(max_batch=2, block_size=4, num_blocks=32,
                 max_prefill_len=8, max_seq_len=32, seed=7,
                 enable_prefix_caching=True)
# a pool tight enough that the six distinct prompts below churn it:
# evictions spill, re-serves hit the spill tier
SPILL_KW = dict(ENGINE_KW, num_blocks=10, spill_max_bytes=1 << 20)

_PROMPT_RNG = np.random.RandomState(5)
PROMPTS = [list(_PROMPT_RNG.randint(1, 40, 8)) for _ in range(6)]


def _engine(tiny_gpt, faults=None, **overrides):
    model, params = tiny_gpt
    kw = dict(ENGINE_KW)
    kw.update(overrides)
    return InferenceEngine(model, params, EngineConfig(**kw),
                           faults=faults, clock=lambda: 0.0)


def _serve_waves(eng, waves=3, new=3):
    """Serve every PROMPT ``waves`` times through a churning pool —
    the spill-tier round trip — returning {uid: tokens}."""
    outs = {}
    for wave in range(waves):
        for k, p in enumerate(PROMPTS):
            eng.add_request(Request(f"w{wave}r{k}", list(p),
                                    max_new_tokens=new))
            outs.update(eng.run())
    return outs


def _fleet(tiny_gpt, n=2, faults=None, fleet_kw=None, **overrides):
    model, params = tiny_gpt
    kw = dict(ENGINE_KW)
    kw.update(overrides)
    return FleetRouter(model, params, EngineConfig(**kw),
                       FleetConfig(num_replicas=n, **(fleet_kw or {})),
                       faults=faults, clock=lambda: 0.0)


# ---------------------------------------------------------------------------
# the checksum/seal primitives
# ---------------------------------------------------------------------------


def test_payload_checksum_content_keyed():
    a = {"k": np.arange(8, dtype=np.float32),
         "v": np.ones(4, np.int8)}
    b = {"v": np.ones(4, np.int8),
         "k": np.arange(8, dtype=np.float32)}
    assert payload_checksum(a) == payload_checksum(b)  # key-order free
    c = {"k": np.arange(8, dtype=np.float32),
         "v": np.zeros(4, np.int8)}
    assert payload_checksum(a) != payload_checksum(c)
    # non-array metadata (the detached transport checksum) is skipped
    d = dict(a, checksum="abc")
    assert payload_checksum(d) == payload_checksum(a)


def test_payload_checksum_covers_dtype_and_shape():
    a = {"k": np.zeros(8, np.float32)}
    assert payload_checksum(a) != payload_checksum(
        {"k": np.zeros(8, np.int32)})
    assert payload_checksum(a) != payload_checksum(
        {"k": np.zeros((2, 4), np.float32)})


def test_record_checksum_stable_across_json_wire():
    # int dict keys are the trap: the wire stringifies them, which
    # reorders sort_keys — the checksum must normalize first
    rec = {"uid": "a", "classes": {10: [1, 2], 9: [3]},
           "pi": 0.1 + 0.2, "t": (1, 2)}
    wired = json.loads(json.dumps(rec))
    assert record_checksum(rec) == record_checksum(wired)


def test_seal_and_verify_record():
    rec = seal_record({"uid": "x", "prompt": [1, 2, 3]})
    assert is_sealed(rec)
    assert verify_record(rec, "test") is True
    assert verify_record({"uid": "x"}, "test") is False  # legacy
    rec["prompt"][0] = 99
    with pytest.raises(IntegrityError, match="test"):
        verify_record(rec, "test")


def test_verify_payload_detached():
    p = {"k": np.arange(4, dtype=np.float32)}
    cs = payload_checksum(p)
    assert verify_payload(p, cs, "t") is True
    assert verify_payload(p, None, "t") is False   # unchecksummed
    p["k"][0] = 7.0
    with pytest.raises(IntegrityError):
        verify_payload(p, cs, "t")


# ---------------------------------------------------------------------------
# the "corrupt" fault kind + perturbation helpers
# ---------------------------------------------------------------------------


def test_corrupt_fault_kind_and_seed():
    plan = FaultPlan([FaultSpec(site="spill_put", kind="corrupt",
                                at=[1])], seed=3)
    assert plan.fire("spill_put") is False
    assert plan.corrupt_seed("spill_put") is None   # index 0: no hit
    # a corrupt hit is its own silent channel — NOT a nan hit (an
    # unvalidated consumer like the train loop's watchdog must not
    # NaN-fill on it)
    assert plan.fire("spill_put") is False
    seed = plan.corrupt_seed("spill_put")
    assert seed == corruption_seed(3, "spill_put", 1)
    # the window is one call wide
    plan.fire("spill_put")
    assert plan.corrupt_seed("spill_put") is None
    # replayable: an identical plan derives the identical seed
    plan2 = FaultPlan([FaultSpec(site="spill_put", kind="corrupt",
                                 at=[1])], seed=3)
    plan2.fire("spill_put")
    plan2.fire("spill_put")
    assert plan2.corrupt_seed("spill_put") == seed
    assert ("spill_put", "corrupt", 1) in plan.fired


def test_perturb_payload_changes_one_array_deterministically():
    p = {"k": np.arange(16, dtype=np.float32),
         "v": np.arange(16, dtype=np.float32)}
    a = perturb_payload(p, 42)
    b = perturb_payload(p, 42)
    assert payload_checksum(a) == payload_checksum(b)   # deterministic
    assert payload_checksum(a) != payload_checksum(p)   # changed
    changed = [k for k in ("k", "v")
               if not np.array_equal(a[k], p[k])]
    assert len(changed) == 1
    # the original is untouched
    assert np.array_equal(p["k"], np.arange(16, dtype=np.float32))


def test_perturb_json_numeric_leaf_only():
    rec = {"uid": "keepme", "prompt": [1, 2, 3], "nested": {"x": 5}}
    a = perturb_json(rec, 7)
    assert a == perturb_json(rec, 7)            # deterministic
    assert a != rec                             # changed
    assert a["uid"] == "keepme"                 # strings intact
    assert rec["prompt"] == [1, 2, 3]           # original intact


def test_perturb_tokens_in_vocab_and_counted():
    toks = np.array([[3, 5, -1], [-1, -1, -1]], np.int32)
    counts = np.array([2, 0])
    out = perturb_tokens(toks, counts, vocab_size=50, seed=9)
    assert np.array_equal(out, perturb_tokens(toks, counts, 50, 9))
    diff = (out != toks)
    assert diff.sum() == 1
    lane, pos = np.argwhere(diff)[0]
    assert lane == 0 and pos < 2                # only valid positions
    assert 0 <= out[lane, pos] < 50
    # nothing to corrupt -> unchanged
    empty = np.full((2, 3), -1, np.int32)
    assert np.array_equal(
        perturb_tokens(empty, np.zeros(2, int), 50, 9), empty)


def test_engine_rejects_bad_fault_site_kind_combos(tiny_gpt):
    with pytest.raises(ValueError, match="integrity sites"):
        _engine(tiny_gpt, faults=FaultPlan(
            [FaultSpec(site="spill_put", kind="transient", every=1)]))
    with pytest.raises(ValueError, match="'decode' only"):
        _engine(tiny_gpt, faults=FaultPlan(
            [FaultSpec(site="prefill", kind="corrupt", every=1)]))
    # corrupt at decode is the supported SDC model
    _engine(tiny_gpt, faults=FaultPlan(
        [FaultSpec(site="decode", kind="corrupt", every=100)]))


def test_integrity_config_validation():
    with pytest.raises(ValueError, match="scrub_interval_ticks"):
        EngineConfig(scrub_interval_ticks=0)
    with pytest.raises(ValueError, match="scrub_spill_blocks"):
        EngineConfig(scrub_spill_blocks=0)
    with pytest.raises(ValueError, match="sdc_check_interval_ticks"):
        FleetConfig(sdc_check_interval_ticks=0)


# ---------------------------------------------------------------------------
# the spill store's checksum discipline
# ---------------------------------------------------------------------------


def _payload(seed=0, n=32):
    rng = np.random.RandomState(seed)
    return {"k": rng.randn(n).astype(np.float32),
            "v": rng.randn(n).astype(np.float32)}


def test_store_clean_roundtrip_and_refused_counter():
    store = HostSpillStore(max_bytes=300)
    assert store.put("h1", _payload(1))
    got = store.pop("h1")
    assert np.array_equal(got["k"], _payload(1)["k"])
    # oversize: refused AND surfaced uniformly in stats
    assert not store.put("big", _payload(2, n=200))
    st = store.stats()
    assert st["refused"] == 1 and st["corrupt_discards"] == 0
    assert st["evictions"] == 1     # back-compat: refusals still count


def test_store_detects_put_side_rot():
    fired = []
    hook_on = {"on": True}

    def rot(site, payload):
        if site == "spill_put" and hook_on["on"]:
            return perturb_payload(payload, 5)
        return payload

    store = HostSpillStore(1 << 20, corrupt_hook=rot,
                           on_corrupt=lambda s, d: fired.append(s))
    store.put("h1", _payload(1))
    assert store.pop("h1") is None          # detected -> miss
    assert store.corrupt_discards == 1
    assert fired == ["spill_get"]           # detection is read-side
    assert "h1" not in store
    # clean entries still serve
    hook_on["on"] = False
    store.put("h2", _payload(2))
    assert store.pop("h2") is not None


def test_store_detects_read_side_rot_on_export():
    def rot(site, payload):
        return (perturb_payload(payload, 6)
                if site == "spill_get" else payload)

    store = HostSpillStore(1 << 20, corrupt_hook=rot)
    store.put("h1", _payload(1))
    assert store.export_entry("h1") is None
    assert store.corrupt_discards == 1
    assert "h1" not in store                # rot -> resident dropped


def test_store_verify_off_trusts_bytes():
    def rot(site, payload):
        return (perturb_payload(payload, 7)
                if site == "spill_put" else payload)

    store = HostSpillStore(1 << 20, verify=False, corrupt_hook=rot)
    store.put("h1", _payload(1))
    assert store.pop("h1") is not None      # the pre-integrity path
    assert store.corrupt_discards == 0


def test_store_scrub_finds_resident_rot():
    def rot(site, payload):
        return (perturb_payload(payload, 8)
                if site == "spill_put" else payload)

    store = HostSpillStore(1 << 20, corrupt_hook=rot,
                           on_corrupt=lambda s, d: sites.append(s))
    sites = []
    store.put("h1", _payload(1))
    verified, corrupt = store.scrub(4)
    assert (verified, corrupt) == (1, 1)
    assert sites == ["scrub"]
    assert len(store) == 0
    assert store.scrub(4) == (0, 0)         # empty store: nothing


def test_store_scrub_walks_round_robin():
    store = HostSpillStore(1 << 20)
    for i in range(5):
        store.put(f"h{i}", _payload(i))
    assert store.scrub(2) == (2, 0)
    assert store.scrub(2) == (2, 0)
    assert store._scrub_cursor == 4         # advanced, not reset


# ---------------------------------------------------------------------------
# engine end-to-end: corrupt artifacts are served by recompute,
# token-identically; integrity off/on is bit-identical on clean runs
# ---------------------------------------------------------------------------


def test_verify_on_off_bit_identical_clean(tiny_gpt):
    a = _engine(tiny_gpt, verify_artifacts=True, **{})
    b = _engine(tiny_gpt, verify_artifacts=False, **{})
    for eng in (a, b):
        for k, p in enumerate(PROMPTS):
            eng.add_request(Request(
                f"r{k}", list(p), max_new_tokens=4,
                sampling=(SamplingParams(temperature=1.0, top_k=10)
                          if k % 2 else SamplingParams())))
    ra = a.run(return_status=True)
    rb = b.run(return_status=True)
    assert {u: (r.tokens, r.status) for u, r in ra.items()} \
        == {u: (r.tokens, r.status) for u, r in rb.items()}
    assert a.stats() == b.stats()


@pytest.mark.parametrize("site", ["spill_put", "spill_get"])
def test_spill_corruption_served_by_recompute_identically(
        tiny_gpt, site):
    model, params = tiny_gpt
    clean_eng = InferenceEngine(model, params, EngineConfig(**SPILL_KW),
                                clock=lambda: 0.0)
    clean = _serve_waves(clean_eng)
    cs = clean_eng.stats()
    assert cs["num_blocks_spilled"] > 0 and cs["spill_hits"] > 0
    plan = FaultPlan([FaultSpec(site=site, kind="corrupt", every=2)],
                     seed=9)
    eng = InferenceEngine(model, params, EngineConfig(**SPILL_KW),
                          faults=plan, clock=lambda: 0.0)
    assert _serve_waves(eng) == clean       # recompute serves, exactly
    st = eng.stats()
    assert st["num_spill_corrupt_discards"] > 0
    assert st["num_corruptions_detected"] \
        == st["num_spill_corrupt_discards"]


def test_scrub_cadence_and_detection(tiny_gpt):
    model, params = tiny_gpt
    plan = FaultPlan([FaultSpec(site="spill_put", kind="corrupt",
                                every=1)], seed=11)
    eng = InferenceEngine(
        model, params,
        EngineConfig(**SPILL_KW, scrub_interval_ticks=1,
                     scrub_spill_blocks=8),
        faults=plan, clock=lambda: 0.0)
    _serve_waves(eng, waves=1)
    st = eng.stats()
    assert st["num_scrubs"] > 0
    assert st["num_scrub_blocks_verified"] > 0
    # EVERY spill was rotten; the scrub (or a read) caught each one
    assert st["num_spill_corrupt_discards"] > 0
    assert st["spill_hits"] == 0


def test_scrub_on_token_identical(tiny_gpt):
    model, params = tiny_gpt
    a = InferenceEngine(model, params, EngineConfig(**SPILL_KW),
                        clock=lambda: 0.0)
    b = InferenceEngine(model, params,
                        EngineConfig(**SPILL_KW, scrub_interval_ticks=2),
                        clock=lambda: 0.0)
    assert _serve_waves(a) == _serve_waves(b)


# ---------------------------------------------------------------------------
# snapshot / checkpoint sealing
# ---------------------------------------------------------------------------


def test_snapshot_sealed_and_wire_restorable(tiny_gpt):
    eng = _engine(tiny_gpt)
    eng.add_request(Request("s0", PROMPTS[0], max_new_tokens=4))
    snap = json.loads(json.dumps(eng.snapshot()))
    assert is_sealed(snap)
    fresh = _engine(tiny_gpt)
    fresh.restore(snap)
    assert fresh.run() == eng.run()


def test_corrupt_snapshot_refuses_restore(tiny_gpt):
    eng = _engine(tiny_gpt)
    eng.add_request(Request("s0", PROMPTS[0], max_new_tokens=4))
    snap = eng.snapshot()
    bad = perturb_json(snap, 13)
    fresh = _engine(tiny_gpt)
    with pytest.raises(IntegrityError, match="restore"):
        fresh.restore(bad)
    assert fresh.stats()["num_corruptions_detected"] == 1
    eng.run()


def test_corrupt_version_field_still_counts_as_corruption(tiny_gpt):
    """Integrity verifies before ANY field is believed — a corruption
    landing on the version leaf must raise IntegrityError (and count),
    not masquerade as an 'unknown snapshot version' ValueError that
    dodges the detection counter."""
    eng = _engine(tiny_gpt)
    eng.add_request(Request("s0", PROMPTS[0], max_new_tokens=2))
    snap = eng.snapshot()
    snap = json.loads(json.dumps(snap))
    snap["version"] = 44
    fresh = _engine(tiny_gpt)
    with pytest.raises(IntegrityError):
        fresh.restore(snap)
    assert fresh.stats()["num_corruptions_detected"] == 1
    eng.run()


def test_legacy_unsealed_snapshot_restores(tiny_gpt):
    eng = _engine(tiny_gpt)
    eng.add_request(Request("s0", PROMPTS[0], max_new_tokens=4))
    snap = eng.snapshot()
    del snap["checksum"]                    # the pre-integrity format
    fresh = _engine(tiny_gpt)
    fresh.restore(snap)
    assert fresh.run() == eng.run()


def test_verify_off_restores_corrupt_snapshot(tiny_gpt):
    # the escape hatch is explicit: verification off trusts the bytes
    eng = _engine(tiny_gpt)
    eng.add_request(Request("s0", PROMPTS[0], max_new_tokens=2))
    snap = eng.snapshot()
    snap["arrival_count"] = snap["arrival_count"] + 0  # keep loadable
    snap["counters"] = dict(snap["counters"], num_ticks=999)  # "rot"
    fresh = _engine(tiny_gpt, verify_artifacts=False)
    fresh.restore(snap)
    eng.run()
    fresh.run()


# ---------------------------------------------------------------------------
# migration records: sealed out, verified in, refused on rot
# ---------------------------------------------------------------------------


def test_clean_export_records_are_sealed_and_import(tiny_gpt):
    src = _engine(tiny_gpt)
    dst = _engine(tiny_gpt)
    src.add_request(Request("m0", PROMPTS[0], max_new_tokens=4))
    recs = src.export_requests()
    assert all(is_sealed(r) for r in recs)
    dst.import_requests(recs)
    assert dst.run()["m0"]


def test_corrupt_export_refused_at_import(tiny_gpt):
    plan = FaultPlan([FaultSpec(site="export", kind="corrupt",
                                at=[0])], seed=3)
    src = _engine(tiny_gpt, faults=plan)
    dst = _engine(tiny_gpt)
    src.add_request(Request("m0", PROMPTS[0], max_new_tokens=4))
    recs = src.export_requests()
    with pytest.raises(IntegrityError, match="import"):
        dst.import_requests(recs)
    st = dst.stats()
    assert st["num_import_refusals"] == 1
    assert st["num_corruptions_detected"] == 1
    assert not dst.has_work                 # refused BEFORE any mutation


def test_import_site_corruption_refused(tiny_gpt):
    # rot on the TARGET side of the wire: the import fire
    src = _engine(tiny_gpt)
    plan = FaultPlan([FaultSpec(site="import", kind="corrupt",
                                at=[0])], seed=4)
    dst = _engine(tiny_gpt, faults=plan)
    src.add_request(Request("m0", PROMPTS[0], max_new_tokens=4))
    with pytest.raises(IntegrityError):
        dst.import_requests(src.export_requests())
    assert not dst.has_work


def test_fleet_migrate_refusal_source_keeps_request(tiny_gpt):
    plans = [FaultPlan([FaultSpec(site="export", kind="corrupt",
                                  every=1)], seed=4), None]
    fl = _fleet(tiny_gpt, n=2, faults=plans)
    fl.add_request(Request("g0", PROMPTS[0], max_new_tokens=4))
    owner = fl.owners()["g0"]
    fl.step()
    moved = fl.migrate(["g0"], owner, dst=1 - owner)
    st = fl.stats()
    assert moved == 0
    assert st["num_refused_imports"] == 1
    assert fl.owners()["g0"] == owner       # the source kept it
    res = fl.run(return_status=True)
    assert res["g0"].status == "finished"
    assert fl.stats()["num_lost_requests"] == 0


def test_corrupt_payload_transport_skipped(tiny_gpt):
    model, params = tiny_gpt
    src = InferenceEngine(model, params, EngineConfig(**SPILL_KW),
                          clock=lambda: 0.0)
    dst = InferenceEngine(model, params, EngineConfig(**SPILL_KW),
                          clock=lambda: 0.0)
    src.add_request(Request("p0", PROMPTS[0], max_new_tokens=3))
    src.run()
    hashes = src._seq_hashes(PROMPTS[0])
    payloads = src.export_prefix_payloads(hashes)
    assert payloads and all("checksum" in p for p in payloads.values())
    # clean transport imports
    assert dst.import_prefix_payloads(payloads) == len(payloads)
    # rotted transport: each corrupt entry skipped + counted
    dst2 = InferenceEngine(model, params, EngineConfig(**SPILL_KW),
                           clock=lambda: 0.0)
    rotted = {h: perturb_payload(p, 21) for h, p in payloads.items()}
    assert dst2.import_prefix_payloads(rotted) == 0
    assert dst2.stats()["num_corruptions_detected"] == len(payloads)


# ---------------------------------------------------------------------------
# fleet: corrupt checkpoints fail over via fresh re-injection
# ---------------------------------------------------------------------------


def test_corrupt_checkpoint_falls_back_to_fresh_reinject(tiny_gpt):
    plans = [FaultPlan([FaultSpec(site="checkpoint", kind="corrupt",
                                  every=1)], seed=5), None]
    fl = _fleet(tiny_gpt, n=2, faults=plans,
                snapshot_interval_ticks=1)
    for k in range(4):
        fl.add_request(Request(f"c{k}", [1 + k] + PROMPTS[0][1:],
                               max_new_tokens=4))
    for _ in range(3):
        fl.step()
    fl.kill_replica(0)
    res = fl.run(return_status=True)
    st = fl.stats()
    assert st["num_corrupt_checkpoints"] >= 1
    assert st["num_lost_requests"] == 0
    assert set(res) == {f"c{k}" for k in range(4)}
    assert all(r.status == "finished" for r in res.values())


def test_failover_placement_refusal_retries_clean_copy(tiny_gpt):
    """A refused FAILOVER placement (in-transit rot at the survivor's
    import site) retries once from the router's clean Request copy
    before giving up: one corruption event must not convert a
    recoverable request into a client-visible failure."""
    plans = [None, FaultPlan([FaultSpec(site="import", kind="corrupt",
                                        at=[0])], seed=8)]
    fl = _fleet(tiny_gpt, n=2, faults=plans)
    fl.add_request(Request("p0", PROMPTS[0], max_new_tokens=4))
    if fl.owners()["p0"] != 0:  # pin the request onto replica 0
        fl.migrate(["p0"], 1, dst=0)
    fl.step()
    fl.kill_replica(0)          # no checkpoint -> fresh re-inject
    res = fl.run(return_status=True)
    st = fl.stats()
    assert st["num_refused_imports"] == 1       # the first hop refused
    assert res["p0"].status == "finished"       # the retry served it
    assert st["num_lost_requests"] == 0


# ---------------------------------------------------------------------------
# the SDC determinism cross-check
# ---------------------------------------------------------------------------


def _sdc_fleet(tiny_gpt, faults=None, n=2, interval=2):
    return _fleet(tiny_gpt, n=n, faults=faults,
                  fleet_kw=dict(sdc_check_interval_ticks=interval))


def _mixed_requests(k=6, new=4):
    return [Request(f"q{i}", [1 + i] + PROMPTS[0][1:],
                    max_new_tokens=new,
                    sampling=(SamplingParams(temperature=1.0, top_k=10)
                              if i % 2 else SamplingParams()))
            for i in range(k)]


def test_sdc_clean_no_suspects_outputs_unchanged(tiny_gpt):
    off = _fleet(tiny_gpt, n=2)
    on = _sdc_fleet(tiny_gpt)
    for fl in (off, on):
        for r in _mixed_requests():
            fl.add_request(Request(r.uid, list(r.prompt),
                                   max_new_tokens=r.max_new_tokens,
                                   sampling=r.sampling))
    ro = off.run(return_status=True)
    rn = on.run(return_status=True)
    assert {u: (r.tokens, r.status) for u, r in ro.items()} \
        == {u: (r.tokens, r.status) for u, r in rn.items()}
    st = on.stats()
    assert st["num_sdc_checks"] > 0
    assert st["num_sdc_suspects"] == 0
    assert st["num_lost_requests"] == 0
    # replays ran under the INTERNAL tenant and never charged a real
    # one: the real tenant's fleet-wide ledger (delivered tokens,
    # statuses) is identical to the sdc-off run; any residual
    # "__sdc__" row is allocator-side cached-block attribution only
    # (honest pool accounting), with its token/status history pruned
    off_t = off.stats()["tenants"]["default"]
    on_t = st["tenants"]["default"]
    assert on_t["tokens"] == off_t["tokens"]
    assert on_t["statuses"] == off_t["statuses"]
    sdc_row = st["tenants"].get("__sdc__")
    if sdc_row is not None:
        assert sdc_row["tokens"] == 0 and sdc_row["statuses"] == {}


def test_sdc_catches_and_retires_corrupt_replica(tiny_gpt):
    plans = [FaultPlan([FaultSpec(site="decode", kind="corrupt",
                                  every=3)], seed=6), None, None]
    fl = _sdc_fleet(tiny_gpt, faults=plans, n=3)
    reqs = _mixed_requests()
    for r in reqs:
        fl.add_request(r)
    res = fl.run(return_status=True)
    st = fl.stats()
    assert st["num_sdc_suspects"] >= 1
    assert not fl.replicas[0].alive
    assert fl.replicas[0].error == "sdc divergence"
    assert st["num_lost_requests"] == 0
    # exactly-once terminals for every accepted uid, replays excluded
    assert set(res) == {r.uid for r in reqs}
    for rep in fl.replicas:
        if rep.alive and rep.engine is not None:
            rep.engine.check_allocator_integrity()


@pytest.mark.parametrize("corrupt_idx", [0, 1, 2])
def test_sdc_arbitration_retires_the_corrupt_replica_only(
        tiny_gpt, corrupt_idx):
    """The majority arbitration: whichever replica carries the corrupt
    chip — the owner of the replayed request OR its first verifier —
    the confirmation replay on an independent third replica sides with
    the healthy majority, the corrupt replica retires, and no healthy
    replica is ever the suspect."""
    plans = [None, None, None]
    plans[corrupt_idx] = FaultPlan(
        [FaultSpec(site="decode", kind="corrupt", every=2)], seed=6)
    fl = _sdc_fleet(tiny_gpt, faults=plans, n=3, interval=1)
    for k in range(9):
        fl.add_request(Request(f"q{k}", [1 + k] + PROMPTS[0][1:],
                               max_new_tokens=4))
    res = fl.run(return_status=True)
    st = fl.stats()
    assert st["num_lost_requests"] == 0
    assert set(res) == {f"q{k}" for k in range(9)}
    assert not fl.replicas[corrupt_idx].alive, "corrupt replica lived"
    assert all(fl.replicas[i].alive for i in range(3)
               if i != corrupt_idx), "a healthy replica was retired"
    assert st["num_sdc_suspects"] >= 1


def test_sdc_rehoming_with_history_drops_eligibility(tiny_gpt):
    """A request re-homed CARRYING generated history mixes two
    replicas' compute in one stream — a later divergence could blame
    the healthy final owner for the previous owner's corruption, so it
    leaves the cross-check pool; a re-homed request with NO history
    (still waiting) stays attributable and stays eligible."""
    fl = _sdc_fleet(tiny_gpt, n=2, interval=1000)   # never launches
    fl.add_request(Request("h0", PROMPTS[0], max_new_tokens=6))
    fl.add_request(Request("h1", PROMPTS[1], max_new_tokens=6))
    assert "h0" in fl._sdc_arrivals and "h1" in fl._sdc_arrivals
    # step until h0's owner has emitted something for it
    owner = fl.owners()["h0"]
    for _ in range(30):
        fl.step()
        if any(s is not None and s.request.uid == "h0" and s.generated
               for s in fl.replicas[owner].engine.slots):
            break
    fl.migrate(None, owner)     # drain everything off the owner
    assert "h0" not in fl._sdc_arrivals     # history rode the record
    res = fl.run(return_status=True)
    assert {u: r.status for u, r in res.items()} \
        == {"h0": "finished", "h1": "finished"}


def test_sdc_replays_never_reach_the_client(tiny_gpt):
    fl = _sdc_fleet(tiny_gpt, interval=1)
    for r in _mixed_requests(4):
        fl.add_request(r)
    seen = []
    while fl.has_work:
        fl.step()
        seen += fl.pop_stream_events()
    res = fl.run(return_status=True)
    assert all(not u.startswith("__sdc__") for u, _, _ in seen)
    assert all(not u.startswith("__sdc__") for u in res)
    assert fl.stats()["num_sdc_checks"] > 0


def test_sdc_sampled_with_speculation_ineligible(tiny_gpt):
    # sampled streams are not replica-invariant under speculation
    # (span boundaries are schedule-dependent): only the greedy
    # requests may enter the replay pool
    fl = _sdc_fleet(tiny_gpt, interval=1)
    fl.engine_config = dataclasses_replace_spec(fl.engine_config)
    sampled = Request("s0", PROMPTS[0], max_new_tokens=3,
                      sampling=SamplingParams(temperature=1.0, top_k=5))
    fl._maybe_capture_sdc("s0", _fake_result([1, 2, 3]))
    assert len(fl._sdc_queue) == 0          # unknown uid: not captured
    # a live greedy request IS captured
    fl.add_request(Request("g0", PROMPTS[1], max_new_tokens=3))
    fl._maybe_capture_sdc("g0", _fake_result([1, 2, 3]))
    assert len(fl._sdc_queue) == 1
    # the sampled one is rejected once speculation is on
    fl.add_request(sampled)
    fl._maybe_capture_sdc("s0", _fake_result([1, 2, 3]))
    assert len(fl._sdc_queue) == 1
    fl.run()


def dataclasses_replace_spec(cfg):
    import dataclasses as _dc

    return _dc.replace(cfg, spec_tokens=2)


def _fake_result(tokens):
    from apex_tpu.serving import RequestResult

    return RequestResult(tokens=list(tokens), status="finished")


# ---------------------------------------------------------------------------
# observability surface
# ---------------------------------------------------------------------------


def test_recorder_kinds_exist():
    for kind in ("corruption_detected", "scrub", "sdc_suspect"):
        assert kind in RECORDER_EVENT_KINDS


def test_corruption_events_reach_the_recorder(tiny_gpt):
    model, params = tiny_gpt
    obs = Observability(metrics=False)
    plan = FaultPlan([FaultSpec(site="spill_put", kind="corrupt",
                                every=1)], seed=12)
    eng = InferenceEngine(
        model, params,
        EngineConfig(**SPILL_KW, scrub_interval_ticks=1),
        faults=plan, clock=lambda: 0.0, obs=obs)
    _serve_waves(eng, waves=1)
    kinds = {e["kind"] for e in obs.recorder.tail()}
    assert "scrub" in kinds
    assert "corruption_detected" in kinds


def _load_tool(name):
    path = Path(__file__).resolve().parents[1] / "tools" / name
    spec = importlib.util.spec_from_file_location(
        f"_{name.removesuffix('.py')}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_trace_summary_integrity_line():
    ts = _load_tool("trace_summary.py")
    dump = {"recorder": {"events": [
        {"kind": "scrub", "t": 0.0, "verified": 4, "corrupt": 1},
        {"kind": "corruption_detected", "t": 0.1, "site": "spill_get"},
        {"kind": "corruption_detected", "t": 0.2, "site": "import"},
        {"kind": "sdc_suspect", "t": 0.3, "replica": 1},
    ]}}
    out = ts.summarize(dump)
    line = [ln for ln in out.splitlines() if "integrity" in ln]
    assert len(line) == 1
    assert "1 scrubs verifying 4 blocks" in line[0]
    assert "2 corruptions caught (import=1, spill_get=1)" in line[0]
    assert "1 SDC suspects retired (replica 1)" in line[0]
    # absent entirely on a clean run
    assert "integrity" not in ts.summarize({"recorder": {"events": []}})


# ---------------------------------------------------------------------------
# tools/bench_diff.py (CI satellite: the bench record gets a consumer)
# ---------------------------------------------------------------------------


def _artifact(tmp_path, name, sections, metrics, rc=0):
    lines = [json.dumps(dict(r, section=s))
             for s, r in sections.items()]
    lines += [json.dumps(dict(r, metric=m))
              for m, r in metrics.items()]
    doc = {"n": 1, "cmd": "bench", "rc": rc,
           "tail": "noise line\n" + "\n".join(lines) + "\n",
           "parsed": None}
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return str(p)


def test_bench_diff_clean_and_deltas(tmp_path):
    bd = _load_tool("bench_diff.py")
    old = _artifact(tmp_path, "old.json",
                    {"bench_a": {"status": "ok", "wall_time_s": 1.0}},
                    {"m1": {"value": 2.0, "unit": "x",
                            "vs_baseline": 2.0}})
    new = _artifact(tmp_path, "new.json",
                    {"bench_a": {"status": "ok", "wall_time_s": 1.5}},
                    {"m1": {"value": 3.0, "unit": "x",
                            "vs_baseline": 3.0}})
    rc, lines = bd.diff(bd.parse_artifact(old), bd.parse_artifact(new))
    assert rc == 0
    joined = "\n".join(lines)
    assert "2 -> 3 (1.500x)" in joined
    assert bd.main([old, new]) == 0


def test_bench_diff_disappeared_section_fails(tmp_path):
    bd = _load_tool("bench_diff.py")
    old = _artifact(tmp_path, "old.json",
                    {"bench_a": {"status": "ok", "wall_time_s": 1.0},
                     "bench_b": {"status": "ok", "wall_time_s": 1.0}},
                    {})
    new = _artifact(tmp_path, "new.json",
                    {"bench_a": {"status": "ok", "wall_time_s": 1.0}},
                    {})
    assert bd.main([old, new]) == 1
    # status regression ok -> failed also fails
    new2 = _artifact(tmp_path, "new2.json",
                     {"bench_a": {"status": "failed",
                                  "wall_time_s": 1.0},
                      "bench_b": {"status": "ok", "wall_time_s": 1.0}},
                     {})
    assert bd.main([old, new2]) == 1
    # additions never fail
    assert bd.main([new, old]) == 0


def test_bench_diff_parses_real_pre_section_artifacts():
    bd = _load_tool("bench_diff.py")
    repo = Path(__file__).resolve().parents[1]
    old = bd.parse_artifact(str(repo / "BENCH_r03.json"))
    new = bd.parse_artifact(str(repo / "BENCH_r04.json"))
    assert old["metrics"] and new["metrics"]
    rc, lines = bd.diff(old, new)
    assert rc == 0                          # no sections -> no liveness
    assert any("pre-PR-6" in ln for ln in lines)
