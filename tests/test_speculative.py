"""Speculative decoding tests (tier-1, CPU): the draft-and-verify
decode path (docs/serving.md) — n-gram/small-GPT drafters, the
rejection-sampling accept rule, greedy bit-identity vs the
non-speculative engine across decode_steps/lane placements/preemption/
snapshot-restore, mid-span EOS, drafter quarantine, block-reservation
rollback, the sampling greedy fast path, and EngineConfig validation."""

import dataclasses as dc

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import GPTConfig, GPTLMHeadModel
from apex_tpu.serving import (
    BlockAllocator,
    Drafter,
    EngineConfig,
    GPTDrafter,
    InferenceEngine,
    NgramDrafter,
    Request,
    SamplingParams,
    sample_tokens,
    sample_tokens_per_lane,
    spec_verify_tokens,
)
from apex_tpu.utils.faults import FaultPlan, FaultSpec


def _tiny_model(**kw):
    kw.setdefault("dropout", 0.0)
    kw.setdefault("remat", False)
    cfg = GPTConfig.tiny(**kw)
    model = GPTLMHeadModel(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    return cfg, model, params


def _engine(model, params, seed=11, **kw):
    base = dict(max_batch=4, block_size=8, num_blocks=64,
                max_prefill_len=16, max_seq_len=64, seed=seed)
    base.update(kw)
    return InferenceEngine(model, params, EngineConfig(**base))


def _greedy_reqs(tag, n=5, seed=37, max_new=None):
    """Staggered all-greedy requests (greedy is the bit-identity
    certification regime; budgets deliberately not span multiples)."""
    rng = np.random.RandomState(seed)
    return [Request(uid=f"{tag}{i}", prompt=list(rng.randint(0, 128, 4 + 2 * i)),
                    max_new_tokens=(max_new or (3 + (i % 3) * 7)))
            for i in range(n)]


def _serve(engine, reqs, stagger=True):
    for r in reqs[:3]:
        engine.add_request(r)
    if stagger:
        engine.step()
        engine.step()
    for r in reqs[3:]:
        engine.add_request(r)
    return engine.run()


class _NullDrafter(Drafter):
    def propose(self, history, max_tokens):
        return []


# ---------------------------------------------------------------------------
# drafters
# ---------------------------------------------------------------------------

def test_ngram_drafter_prompt_lookup():
    d = NgramDrafter(max_ngram=3, min_ngram=1)
    # suffix [7, 8] occurred earlier; propose its continuation
    assert d.propose([7, 8, 9, 1, 7, 8], 3) == [9, 1, 7]
    # longest suffix match wins over a shorter, more recent one
    assert d.propose([1, 2, 3, 9, 3, 1, 2, 3], 2) == [9, 3]
    # the LATEST earlier occurrence of the n-gram is used
    assert d.propose([5, 4, 5, 6, 5], 1) == [6]
    # a continuation that runs into the present extends periodically
    assert d.propose([1, 2, 1, 2], 8) == [1, 2, 1, 2, 1, 2, 1, 2]
    # no earlier occurrence -> no proposal; short history -> none
    assert d.propose([1, 2, 3, 4], 4) == []
    assert d.propose([3], 4) == []
    assert d.propose([1, 2, 1], 0) == []
    with pytest.raises(ValueError, match="min_ngram"):
        NgramDrafter(max_ngram=0)
    with pytest.raises(ValueError, match="min_ngram"):
        NgramDrafter(max_ngram=2, min_ngram=3)


def test_gpt_drafter_is_deterministic_and_validates():
    cfg, model, params = _tiny_model()
    d = GPTDrafter(model, params, window=8)
    hist = [3, 1, 4, 1, 5]
    a = d.propose(hist, 4)
    assert len(a) == 4 and all(0 <= t < cfg.vocab_size for t in a)
    # pure function of the history (the resume-determinism contract)
    assert d.propose(list(hist), 4) == a
    # proposals chain: the first k of a longer proposal are the
    # proposal for k tokens
    assert d.propose(hist, 2) == a[:2]
    with pytest.raises(ValueError, match="window"):
        GPTDrafter(model, params, window=0)
    with pytest.raises(ValueError, match="max_position_embeddings"):
        GPTDrafter(model, params, window=10 ** 6)


# ---------------------------------------------------------------------------
# the accept rule
# ---------------------------------------------------------------------------

def test_spec_verify_tokens_greedy_accept_rule():
    """Hand-built logits: greedy lanes accept exactly the prefix of
    drafts that equal each position's argmax, and the final token is
    the first-rejection argmax (or the bonus argmax past the span)."""
    B, S, V = 3, 3, 16
    P = S + 1
    lg = np.full((B, P, V), -10.0, np.float32)
    argmax = np.array([[4, 5, 6, 7],
                       [3, 2, 1, 0],
                       [9, 9, 9, 9]])
    for b in range(B):
        for p in range(P):
            lg[b, p, argmax[b, p]] = 10.0
    drafts = jnp.asarray([[4, 5, 6],     # all accepted -> bonus 7
                          [3, 9, 1],     # reject at pos 1 -> correct 2
                          [0, 0, 0]], jnp.int32)   # reject at 0 -> 9
    dlens = jnp.asarray([3, 3, 2], jnp.int32)
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(B))
    tidx = jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32)[None], (B, P))
    zeros = jnp.zeros(B, jnp.float32)
    emitted, n_emit = spec_verify_tokens(
        jnp.asarray(lg), drafts, dlens, keys, tidx,
        zeros, jnp.zeros(B, jnp.int32), jnp.ones(B, jnp.float32))
    emitted, n_emit = np.asarray(emitted), np.asarray(n_emit)
    assert list(n_emit) == [4, 2, 1]
    assert list(emitted[0]) == [4, 5, 6, 7]
    assert list(emitted[1][:2]) == [3, 2]
    assert list(emitted[2][:1]) == [9]


def test_spec_verify_tokens_sampled_is_distribution_preserving():
    """The rejection rule must reproduce the target distribution
    exactly: over many keys, the first emitted token's histogram under
    drafting matches direct sampling from the same (filtered) target
    distribution — the Leviathan et al. guarantee."""
    V = 8
    logits = jnp.asarray(np.linspace(0.0, 2.0, V, dtype=np.float32))[None]
    target = np.asarray(jax.nn.softmax(logits[0]))
    n = 4000
    draft = jnp.full((n, 1), 5, jnp.int32)   # a fixed, mediocre guess
    dlens = jnp.ones(n, jnp.int32)
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(n))
    lg = jnp.broadcast_to(logits[:, None, :], (n, 2, V))
    tidx = jnp.broadcast_to(jnp.arange(2, dtype=jnp.int32)[None], (n, 2))
    emitted, _ = spec_verify_tokens(
        lg, draft, dlens, keys, tidx,
        jnp.ones(n, jnp.float32), jnp.zeros(n, jnp.int32),
        jnp.ones(n, jnp.float32))
    first = np.asarray(emitted[:, 0])
    hist = np.bincount(first, minlength=V) / n
    # generous tolerance: 4000 draws, max std ~0.008
    np.testing.assert_allclose(hist, target, atol=0.035)


# ---------------------------------------------------------------------------
# engine: greedy bit-identity certification matrix
# ---------------------------------------------------------------------------

def test_speculative_greedy_bit_identical_across_k_and_spec():
    """THE speculative acceptance scenario: greedy output is
    bit-identical between non-speculative engines at decode_steps in
    {1, 4, 8} and speculative engines at spec_tokens in {2, 4, 8},
    over a staggered multi-lane workload; compile counts stay pinned
    at one prefill + one decode program; and the drafter actually
    accepts tokens (fewer dispatches than K=1 for the same stream)."""
    cfg, model, params = _tiny_model()
    outs, stats = {}, {}
    for arm, kw in {"k1": dict(decode_steps=1),
                    "k4": dict(decode_steps=4),
                    "k8": dict(decode_steps=8),
                    "s2": dict(spec_tokens=2),
                    "s4": dict(spec_tokens=4),
                    "s8": dict(spec_tokens=8)}.items():
        engine = _engine(model, params, **kw)
        outs[arm] = _serve(engine, _greedy_reqs("m"))
        s = engine.stats()
        assert s["prefill_compilations"] == 1
        assert s["decode_compilations"] == 1
        assert engine.allocator.num_used == 0
        stats[arm] = s
    first = outs["k1"]
    assert all(o == first for o in outs.values())
    for arm in ("s2", "s4", "s8"):
        assert stats[arm]["num_draft_tokens"] > 0
        assert stats[arm]["num_accepted_tokens"] > 0
        assert 0.0 < stats[arm]["draft_acceptance_rate"] <= 1.0
        assert (stats[arm]["num_accepted_tokens"]
                <= stats[arm]["num_draft_tokens"])
        # >1 token per target forward on average is the whole point
        assert (stats[arm]["num_decode_dispatches"]
                < stats["k1"]["num_decode_dispatches"])
        assert (stats[arm]["num_tokens_decoded"]
                == stats["k1"]["num_tokens_decoded"])


def test_speculative_sampled_null_drafter_bit_identical():
    """A speculative engine whose drafter proposes NOTHING runs the
    verify program as plain single-token decoding — and because the
    bonus token is keyed exactly like the non-speculative token at the
    same index, even SAMPLED lanes are bit-identical to spec-off."""
    cfg, model, params = _tiny_model()
    rng = np.random.RandomState(7)
    reqs = [Request(uid=f"s{i}", prompt=list(rng.randint(0, 128, 5 + i)),
                    max_new_tokens=9,
                    sampling=(SamplingParams(temperature=0.9, top_k=12,
                                             top_p=0.85)
                              if i % 2 else SamplingParams()))
            for i in range(4)]
    base = _engine(model, params)
    out_base = _serve(base, reqs, stagger=False)
    spec = InferenceEngine(model, params, EngineConfig(
        max_batch=4, block_size=8, num_blocks=64, max_prefill_len=16,
        max_seq_len=64, seed=11, spec_tokens=3), drafter=_NullDrafter())
    out_spec = _serve(spec, reqs, stagger=False)
    assert out_spec == out_base
    s = spec.stats()
    assert s["num_draft_tokens"] == 0
    assert s["decode_compilations"] == 1


def test_speculative_sampled_lanes_accept_and_greedy_stay_identical():
    """With a real drafter and sampled lanes in the mix: greedy lanes
    remain bit-identical to the non-speculative engine (the structural
    argmax identity holds regardless of proposals), sampled lanes keep
    their budgets/lengths, and the run is deterministic (re-serving
    reproduces it bit-for-bit)."""
    cfg, model, params = _tiny_model()
    rng = np.random.RandomState(3)
    reqs = [Request(uid=f"x{i}", prompt=list(rng.randint(0, 128, 6)),
                    max_new_tokens=12,
                    sampling=(SamplingParams(temperature=1.0, top_k=20)
                              if i % 2 else SamplingParams()))
            for i in range(4)]
    out_base = _serve(_engine(model, params), reqs, stagger=False)
    out_a = _serve(_engine(model, params, spec_tokens=4), reqs,
                   stagger=False)
    out_b = _serve(_engine(model, params, spec_tokens=4), reqs,
                   stagger=False)
    assert out_a == out_b                      # deterministic
    for i in (0, 2):                           # greedy lanes: identical
        assert out_a[f"x{i}"] == out_base[f"x{i}"]
    for i in (1, 3):                           # sampled lanes: full runs
        assert len(out_a[f"x{i}"]) == len(out_base[f"x{i}"]) == 12


def test_speculative_mid_span_eos_truncates_like_k1():
    """EOS accepted (or corrected) mid-verify-span must cut the lane's
    remaining emission on-device and finish it on exactly the token a
    non-speculative K=1 engine finishes on."""
    cfg, model, params = _tiny_model()
    prompt = list(np.random.RandomState(31).randint(0, 128, 6))
    pilot = _engine(model, params)
    pilot.add_request(Request(uid="p", prompt=prompt, max_new_tokens=8))
    ref = pilot.run()["p"]
    eos = int(ref[3])
    expected = ref[: ref.index(eos) + 1]
    engine = _engine(model, params, spec_tokens=8)
    engine.add_request(Request(uid="e", prompt=prompt, max_new_tokens=8,
                               eos_token_id=eos))
    engine.add_request(Request(uid="b", prompt=prompt, max_new_tokens=8))
    out = engine.run()
    assert out["e"] == expected
    assert out["b"] == ref
    assert engine.allocator.num_used == 0
    assert engine.stats()["decode_compilations"] == 1


def test_speculative_preemption_resume_is_deterministic():
    """Preemption at speculative-span granularity: a pool tight enough
    to preempt mid-stream must emit byte-identical greedy tokens to a
    roomy speculative pool AND to a roomy non-speculative engine —
    emitted tokens are carried across preemption and re-prefill
    re-derives the lane, drafts and all."""
    cfg, model, params = _tiny_model()
    rng = np.random.RandomState(19)
    reqs = [Request(uid=f"r{i}", prompt=list(rng.randint(0, 128, 6 + i)),
                    max_new_tokens=20)
            for i in range(3)]

    def serve(num_blocks, **kw):
        engine = InferenceEngine(model, params, EngineConfig(
            max_batch=3, block_size=8, num_blocks=num_blocks,
            max_prefill_len=8, max_seq_len=32, seed=5, **kw))
        for r in reqs:
            engine.add_request(r)
        return engine.run(), engine.stats()

    roomy, roomy_stats = serve(num_blocks=16, spec_tokens=4)
    tight, tight_stats = serve(num_blocks=6, spec_tokens=4)
    plain, plain_stats = serve(num_blocks=16)
    assert roomy_stats["num_preemptions"] == 0
    assert tight_stats["num_preemptions"] >= 1
    assert tight == roomy == plain
    for s in (roomy_stats, tight_stats, plain_stats):
        assert s["prefill_compilations"] == 1
        assert s["decode_compilations"] == 1


def test_speculative_snapshot_restore_bit_identical():
    """A snapshot taken mid-stream of a speculative engine restores
    into a fresh speculative engine and completes bit-identically to
    the uninterrupted run (the PR 6 crash-consistency contract holds
    with drafting on; the config fingerprint covers spec_tokens)."""
    cfg, model, params = _tiny_model()
    reqs = _greedy_reqs("c", n=4, seed=9, max_new=14)
    ref_engine = _engine(model, params, spec_tokens=4)
    uninterrupted = _serve(ref_engine, reqs, stagger=False)

    eng = _engine(model, params, spec_tokens=4)
    for r in reqs:
        eng.add_request(r)
    for _ in range(4):
        eng.step()
    snap = eng.snapshot()
    fresh = _engine(model, params, spec_tokens=4)
    fresh.restore(snap)
    merged = dict(snap["finished"])
    merged.update(fresh.run())
    assert merged == uninterrupted
    # a non-speculative engine must refuse the speculative snapshot
    with pytest.raises(ValueError, match="spec_tokens"):
        _engine(model, params).restore(snap)


def test_speculative_with_prefix_caching_reuses_blocks():
    """Drafting composes with prefix caching: the second serving of an
    identical prompt matches its cached blocks (zero prompt-block
    allocations) and still emits the same greedy tokens; span-
    reservation rollback never trims a prefix-registered block."""
    cfg, model, params = _tiny_model()
    prompt = list(np.random.RandomState(4).randint(0, 128, 16))
    engine = _engine(model, params, spec_tokens=4,
                     enable_prefix_caching=True)
    engine.add_request(Request(uid="a", prompt=prompt, max_new_tokens=10))
    first = engine.run()["a"]
    allocated = engine.stats()["prompt_blocks_allocated"]
    engine.add_request(Request(uid="b", prompt=prompt, max_new_tokens=10))
    second = engine.run()["b"]
    assert second == first
    assert engine.stats()["prompt_blocks_allocated"] == allocated
    assert engine.stats()["prefix_hit_blocks"] >= 2


# ---------------------------------------------------------------------------
# drafter quarantine (degrade, don't die)
# ---------------------------------------------------------------------------

def test_crashing_drafter_degrades_to_nonspeculative():
    """A drafter whose propose keeps failing transiently exhausts the
    shared retry policy and is QUARANTINED: speculation flips off for
    the engine's lifetime and the verify program keeps emitting
    bit-identical tokens as plain decode — the engine never dies."""
    cfg, model, params = _tiny_model()
    reqs = _greedy_reqs("q", n=4, seed=2, max_new=10)
    out_base = _serve(_engine(model, params), reqs, stagger=False)
    plan = FaultPlan(specs=[FaultSpec(site="draft", kind="transient",
                                      every=1)], seed=0)
    engine = InferenceEngine(
        model, params,
        EngineConfig(max_batch=4, block_size=8, num_blocks=64,
                     max_prefill_len=16, max_seq_len=64, seed=11,
                     spec_tokens=4, max_dispatch_retries=1),
        faults=plan)
    out = _serve(engine, reqs, stagger=False)
    assert out == out_base
    s = engine.stats()
    assert s["num_drafter_quarantines"] == 1
    assert s["num_draft_retries"] >= 1
    assert s["speculation_active"] == 0
    assert s["num_draft_tokens"] == 0
    assert s["num_quarantines"] == 0          # no REQUEST was failed


def test_buggy_drafter_quarantined_without_retry_eating_the_bug():
    """A drafter that raises a non-transient exception (a plain bug) is
    quarantined immediately — the engine degrades instead of dying, and
    outputs stay bit-identical to non-speculative decode."""
    cfg, model, params = _tiny_model()

    class Buggy(Drafter):
        def propose(self, history, max_tokens):
            raise ZeroDivisionError("drafter bug")

    reqs = _greedy_reqs("z", n=3, seed=6, max_new=8)
    out_base = _serve(_engine(model, params), reqs, stagger=False)
    engine = InferenceEngine(model, params, EngineConfig(
        max_batch=4, block_size=8, num_blocks=64, max_prefill_len=16,
        max_seq_len=64, seed=11, spec_tokens=4), drafter=Buggy())
    out = _serve(engine, reqs, stagger=False)
    assert out == out_base
    assert engine.stats()["num_drafter_quarantines"] == 1
    assert engine.stats()["speculation_active"] == 0


def test_drafter_quarantine_survives_snapshot_restore():
    """Quarantine is part of the engine's behavioral state: a snapshot
    taken after the drafter was quarantined restores DEGRADED, even
    into an engine handed a healthy drafter. Resumed speculation would
    draw accept/resample uniforms the uninterrupted (empty-plan) run
    never drew, so a sampled lane would diverge from the
    crash-consistency contract — the restored run must stay
    bit-identical to the uninterrupted degraded one."""
    cfg, model, params = _tiny_model()

    class Buggy(Drafter):
        def propose(self, history, max_tokens):
            raise ZeroDivisionError("drafter bug")

    rng = np.random.RandomState(5)
    pat = list(rng.randint(0, 128, 3))
    reqs = [
        # a repetitive sampled lane: exactly where a healthy n-gram
        # drafter WOULD propose (and shift the key chain) post-restore
        Request(uid="s0", prompt=(pat * 6)[:14], max_new_tokens=12,
                sampling=SamplingParams(temperature=0.8, top_k=32)),
        Request(uid="g0", prompt=(pat * 5)[:12], max_new_tokens=10),
        Request(uid="g1", prompt=list(rng.randint(0, 128, 8)),
                max_new_tokens=8),
    ]

    def fresh_reqs():
        return [dc.replace(r) for r in reqs]

    ecfg = dict(spec_tokens=4)
    ref = InferenceEngine(model, params, EngineConfig(
        max_batch=4, block_size=8, num_blocks=64, max_prefill_len=16,
        max_seq_len=64, seed=11, **ecfg), drafter=Buggy())
    for r in fresh_reqs():
        ref.add_request(r)
    uninterrupted = ref.run()
    assert ref.stats()["speculation_active"] == 0

    eng = InferenceEngine(model, params, EngineConfig(
        max_batch=4, block_size=8, num_blocks=64, max_prefill_len=16,
        max_seq_len=64, seed=11, **ecfg), drafter=Buggy())
    for r in fresh_reqs():
        eng.add_request(r)
    for _ in range(3):
        eng.step()
    assert eng.stats()["speculation_active"] == 0   # quarantine fired
    snap = eng.snapshot()

    restored = InferenceEngine(model, params, EngineConfig(
        max_batch=4, block_size=8, num_blocks=64, max_prefill_len=16,
        max_seq_len=64, seed=11, **ecfg), drafter=NgramDrafter())
    restored.restore(snap)
    assert restored.stats()["speculation_active"] == 0
    merged = dict(snap["finished"])
    merged.update(restored.run())
    assert merged == uninterrupted
    assert restored.stats()["num_draft_tokens"] == 0


def test_out_of_vocab_proposals_are_truncated():
    """Proposals are sanitized at the first out-of-vocabulary token:
    the lane verifies the clean prefix, output stays bit-identical."""
    cfg, model, params = _tiny_model()

    class Wild(Drafter):
        def __init__(self):
            self.inner = NgramDrafter()

        def propose(self, history, max_tokens):
            good = self.inner.propose(history, max_tokens)
            return good[:1] + [10 ** 9] + good[1:]

    reqs = _greedy_reqs("w", n=3, seed=8, max_new=10)
    out_base = _serve(_engine(model, params), reqs, stagger=False)
    engine = InferenceEngine(model, params, EngineConfig(
        max_batch=4, block_size=8, num_blocks=64, max_prefill_len=16,
        max_seq_len=64, seed=11, spec_tokens=4), drafter=Wild())
    out = _serve(engine, reqs, stagger=False)
    assert out == out_base
    assert engine.stats()["speculation_active"] == 1


# ---------------------------------------------------------------------------
# block-reservation rollback
# ---------------------------------------------------------------------------

def test_trim_to_releases_private_tail_and_guards_shared():
    a = BlockAllocator(8)
    blocks = a.alloc(5)
    kept = a.trim_to(blocks, 2)
    assert kept == blocks[:2]
    assert a.num_free == 6
    # shared tail: refcount != 1 must refuse before freeing anything
    a.acquire([kept[1]])
    with pytest.raises(ValueError, match="refcount"):
        a.trim_to(kept, 0)
    assert a.num_free == 6                    # nothing was released
    # prefix-registered tail must refuse too (it is matchable context)
    b = a.alloc(1)
    a.register_prefix("h0", b[0])
    with pytest.raises(ValueError, match="prefix"):
        a.trim_to(b, 0)
    with pytest.raises(ValueError, match="keep"):
        a.trim_to(kept, 3)


def test_speculative_rollback_returns_stranded_blocks():
    """A rejection that leaves a lane short of its reserved span must
    return the stranded blocks to the pool at drain time (observable
    via the rollback counter), and the allocator must balance to zero
    when the workload finishes."""
    cfg, model, params = _tiny_model()
    # block_size=2 makes every span cross block boundaries, so any
    # rejection strands at least one block
    engine = _engine(model, params, spec_tokens=6, block_size=2,
                     num_blocks=128, max_seq_len=48)
    for r in _greedy_reqs("t", n=4, seed=12, max_new=12):
        engine.add_request(r)
    engine.run()
    s = engine.stats()
    assert s["num_draft_tokens"] > 0
    assert engine.allocator.num_used == 0
    if s["num_accepted_tokens"] < s["num_draft_tokens"]:
        assert s["num_spec_blocks_rolled_back"] > 0


# ---------------------------------------------------------------------------
# sampling greedy fast path (satellite)
# ---------------------------------------------------------------------------

def test_greedy_fast_path_bit_identity():
    """temperature == 0 everywhere short-circuits the sort/filter/
    softmax chain to argmax — and must be bit-identical to the mixed-
    batch path's greedy rows (which still run the full chain's
    where-select)."""
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(6, 64).astype(np.float32))
    argmax = np.argmax(np.asarray(logits), axis=-1)
    zeros = jnp.zeros(6, jnp.float32)
    k0 = jnp.zeros(6, jnp.int32)
    p1 = jnp.ones(6, jnp.float32)
    key = jax.random.PRNGKey(0)
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(6))

    fast = sample_tokens(logits, key, zeros, k0, p1)
    np.testing.assert_array_equal(np.asarray(fast), argmax)
    fast_l = sample_tokens_per_lane(logits, keys, zeros, k0, p1)
    np.testing.assert_array_equal(np.asarray(fast_l), argmax)

    # mixed batch: row 3 samples, every greedy row must STILL be argmax
    mixed_t = zeros.at[3].set(0.9)
    mixed = np.asarray(sample_tokens(logits, key, mixed_t, k0, p1))
    mixed_l = np.asarray(sample_tokens_per_lane(logits, keys, mixed_t,
                                                k0, p1))
    for row in (0, 1, 2, 4, 5):
        assert mixed[row] == argmax[row]
        assert mixed_l[row] == argmax[row]


# ---------------------------------------------------------------------------
# EngineConfig validation (satellite)
# ---------------------------------------------------------------------------

def test_engine_config_validation_rejects_bad_geometry():
    good = dict(max_batch=2, block_size=8, num_blocks=16,
                max_prefill_len=16, max_seq_len=32)
    EngineConfig(**good)                      # sanity: valid
    with pytest.raises(ValueError, match="block_size"):
        EngineConfig(**{**good, "block_size": 0})
    with pytest.raises(ValueError, match="num_blocks"):
        EngineConfig(**{**good, "num_blocks": -1})
    with pytest.raises(ValueError, match="exceeds max_seq_len"):
        EngineConfig(**{**good, "prefill_chunk": 64})
    with pytest.raises(ValueError, match="prefill_chunk"):
        EngineConfig(**{**good, "prefill_chunk": 0})
    with pytest.raises(ValueError, match="exceeds max_seq_len"):
        # prefill_chunk=None inherits max_prefill_len, which must obey
        # the same bound
        EngineConfig(**{**good, "max_prefill_len": 64})
    with pytest.raises(ValueError, match="decode_steps"):
        EngineConfig(**{**good, "decode_steps": 0})
    with pytest.raises(ValueError, match="spec_tokens"):
        EngineConfig(**{**good, "spec_tokens": -1})
    with pytest.raises(ValueError, match="max_dispatch_retries"):
        EngineConfig(**{**good, "max_dispatch_retries": -1})


def test_engine_rejects_drafter_without_spec_tokens():
    cfg, model, params = _tiny_model()
    with pytest.raises(ValueError, match="spec_tokens"):
        InferenceEngine(model, params, EngineConfig(
            max_batch=2, block_size=8, num_blocks=16, max_prefill_len=16,
            max_seq_len=32), drafter=NgramDrafter())


# ---------------------------------------------------------------------------
# dynamic speculation (spec_adapt — docs/serving.md)
# ---------------------------------------------------------------------------


class _WrongDrafter(Drafter):
    """Adversarial drafter: proposes a constant (almost always
    rejected) token — the low-acceptance regime spec_adapt exists
    for."""

    def __init__(self, token):
        self._t = int(token)

    def propose(self, history, max_tokens):
        return [self._t] * max_tokens


def _adapt_engine(model, params, cfg, **kw):
    base = dict(max_batch=4, block_size=8, num_blocks=64,
                max_prefill_len=16, max_seq_len=64, seed=11,
                spec_tokens=4)
    base.update(kw)
    return InferenceEngine(model, params, EngineConfig(**base),
                           drafter=_WrongDrafter(cfg.vocab_size - 1))


def _repetitive_reqs(tag, n=4, max_new=10):
    """Highly structured prompts: prompt-lookup acceptance ~1, the
    regime where the adaptive cap must never move."""
    return [Request(uid=f"{tag}{i}",
                    prompt=[5, 6, 7, 8] * (2 + i % 2),
                    max_new_tokens=max_new)
            for i in range(n)]


def test_spec_adapt_high_acceptance_bit_identical_to_static():
    cfg, model, params = _tiny_model()
    outs, stats = {}, {}
    for arm, kw in {"static": dict(spec_tokens=4),
                    "adapt": dict(spec_tokens=4, spec_adapt=True)}.items():
        engine = _engine(model, params, **kw)
        outs[arm] = _serve(engine, _repetitive_reqs("h"))
        stats[arm] = engine.stats()
    assert outs["adapt"] == outs["static"]
    # acceptance stayed above the high threshold: the cap never moved,
    # and the SCHEDULE matched too (same dispatch count)
    assert stats["adapt"]["spec_cap"] == 4
    assert stats["adapt"]["num_spec_cap_shrinks"] == 0
    assert stats["adapt"]["draft_acceptance_rate"] > 0.8
    assert (stats["adapt"]["num_decode_dispatches"]
            == stats["static"]["num_decode_dispatches"])


def test_spec_adapt_caps_out_under_rejecting_drafter():
    cfg, model, params = _tiny_model()
    adapt = _adapt_engine(model, params, cfg, spec_adapt=True)
    rng = np.random.RandomState(3)
    reqs = [Request(uid=f"c{i}", prompt=list(rng.randint(0, 128, 8)),
                    max_new_tokens=30) for i in range(2)]
    for r in reqs:
        adapt.add_request(r)
    out = adapt.run()
    s = adapt.stats()
    # the cap walked all the way down (4 shrink steps), so the engine
    # stopped paying for spans it always rejects...
    assert s["spec_cap"] == 0
    assert s["num_spec_cap_shrinks"] == 4
    assert s["speculation_active"] == 1     # not quarantined: adaptive
    # ...while greedy output stays bit-identical to the non-speculative
    # engine (the rejection rule never let a wrong draft through)
    base = _engine(model, params)
    for r in reqs:
        base.add_request(Request(uid=r.uid, prompt=r.prompt,
                                 max_new_tokens=r.max_new_tokens))
    assert out == base.run()
    # a static engine with the same drafter keeps drafting full spans:
    # the adaptive engine drafted strictly less
    static = _adapt_engine(model, params, cfg)
    for r in reqs:
        static.add_request(Request(uid=r.uid, prompt=r.prompt,
                                   max_new_tokens=r.max_new_tokens))
    static.run()
    assert s["num_draft_tokens"] < static.stats()["num_draft_tokens"]


def test_spec_adapt_cap_rides_snapshot_overload_section():
    cfg, model, params = _tiny_model()
    a = _adapt_engine(model, params, cfg, spec_adapt=True)
    a.add_request(Request(uid="s", prompt=[3, 9, 4, 1, 7],
                          max_new_tokens=24))
    for _ in range(10):
        a.step()
    snap = a.snapshot()
    assert snap["overload"]["spec_cap"] < 4   # mid-walk
    # an adapting engine resumes the walk exactly...
    b = _adapt_engine(model, params, cfg, spec_adapt=True)
    b.restore(snap)
    assert b.stats()["spec_cap"] == snap["overload"]["spec_cap"]
    out_b = b.run()
    # ...and a NON-adapting engine ignores it (it could never restore
    # the cap — same guard shape as the ladder rung)
    c = _adapt_engine(model, params, cfg)
    c.restore(snap)
    assert c.stats()["spec_cap"] == 4
    out_c = c.run()
    # greedy continuation identical either way (and to uninterrupted)
    out_a = a.run()
    assert out_b == out_a == out_c
