"""Mixture-of-experts / expert-parallelism tests.

The reference (apex) has no MoE tier; these tests validate the
TPU-native extension (apex_tpu/transformer/moe.py) the same way the TP
tests validate sharded layers: an independent per-token numpy reference
for the routing/expert math, and shard_map expert-parallel runs checked
against the assembled single-device equivalent on the 8-device CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.moe import MoEMLP, route_top_k


def _np_route_top_k(logits, k, capacity):
    """Independent greedy-rounds router: round r assigns every token its
    r-th choice in token order, dropping tokens once an expert is full
    (matching route_top_k's GShard ordering)."""
    T, E = logits.shape
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs = probs / probs.sum(-1, keepdims=True)
    dispatch = np.zeros((T, E, capacity))
    combine = np.zeros((T, E, capacity))
    banned = np.zeros((T, E), bool)
    fill = np.zeros(E, int)
    for _ in range(k):
        masked = np.where(banned, -np.inf, probs)
        choice = masked.argmax(-1)
        for t in range(T):
            e = choice[t]
            if fill[e] < capacity:
                dispatch[t, e, fill[e]] = 1.0
                combine[t, e, fill[e]] = probs[t, e]
                fill[e] += 1
            banned[t, e] = True
    return dispatch, combine


def _np_expert_mlp(tokens, combine, w1, b1, w2, b2):
    """Per-token loop: y[t] = sum_e sum_c combine[t,e,c] * expert_e(x[t])."""
    T, H = tokens.shape
    y = np.zeros((T, H))
    gates = combine.sum(-1)  # (T, E)
    for t in range(T):
        for e in range(w1.shape[0]):
            if gates[t, e] > 0:
                h = tokens[t] @ w1[e] + b1[e]
                h = np.asarray(jax.nn.gelu(jnp.asarray(h)))
                y[t] += gates[t, e] * (h @ w2[e] + b2[e])
    return y


def test_route_top1_matches_numpy_reference():
    rng = np.random.RandomState(0)
    logits = rng.randn(16, 4).astype("float32")
    cap = 16  # no drops
    out = route_top_k(jnp.asarray(logits), 1, cap)
    d_ref, c_ref = _np_route_top_k(logits, 1, cap)
    np.testing.assert_allclose(np.asarray(out.dispatch), d_ref, atol=1e-6)
    np.testing.assert_allclose(np.asarray(out.combine), c_ref, rtol=1e-5,
                               atol=1e-6)
    # every token dispatched exactly once at full capacity
    assert np.asarray(out.dispatch).sum() == 16


def test_route_top2_capacity_drops():
    # all tokens prefer expert 0; capacity 2 keeps only the first two
    # primaries there, the rest overflow (their primary slot is dropped)
    logits = np.full((6, 3), -5.0, "float32")
    logits[:, 0] = 5.0
    logits[:, 1] = 0.0
    out = route_top_k(jnp.asarray(logits), 2, 2)
    d = np.asarray(out.dispatch)
    assert d[:, 0].sum() == 2          # expert 0 full at capacity
    assert d[:2, 0].sum() == 2         # ...with the first two tokens
    assert d[:, 1].sum() == 2          # secondaries queue on expert 1 too
    d_ref, c_ref = _np_route_top_k(logits, 2, 2)
    np.testing.assert_allclose(d, d_ref, atol=1e-6)
    np.testing.assert_allclose(np.asarray(out.combine), c_ref, rtol=1e-5,
                               atol=1e-6)


def test_route_aux_loss_uniform_is_one():
    # perfectly uniform routing minimizes the Switch aux loss at 1.0
    T, E = 32, 4
    logits = np.zeros((T, E), "float32")
    logits[np.arange(T), np.arange(T) % E] = 20.0  # equal shares
    out = route_top_k(jnp.asarray(logits), 1, T)
    np.testing.assert_allclose(float(out.aux_loss), 1.0, rtol=1e-3)


@pytest.mark.parametrize("top_k", [1, 2])
@pytest.mark.slow
def test_moe_mlp_matches_per_token_reference(top_k):
    """ep=1 (no mesh): MoEMLP == independent per-token numpy loop."""
    T, H, F, E = 12, 8, 16, 4
    rng = np.random.RandomState(1)
    x = rng.randn(T, H).astype("float32")
    layer = MoEMLP(hidden_size=H, ffn_hidden_size=F, num_experts=E,
                   top_k=top_k, capacity_factor=8.0,  # no drops
                   dtype=jnp.float32)
    params = layer.init(jax.random.PRNGKey(0), jnp.asarray(x))
    y, aux, z = layer.apply(params, jnp.asarray(x))

    p = jax.tree.map(np.asarray, params["params"])
    cap = max(1, int(-(-top_k * T * 8.0 // E)))
    logits = x @ p["router"]
    _, combine = _np_route_top_k(logits, top_k, cap)
    y_ref = _np_expert_mlp(x, combine, p["w1"], p["b1"], p["w2"], p["b2"])
    np.testing.assert_allclose(np.asarray(y), y_ref, rtol=1e-4, atol=1e-5)
    assert float(aux) > 0 and float(z) >= 0


@pytest.mark.slow
def test_moe_mlp_grads_flow():
    T, H, F, E = 8, 4, 8, 2
    x = jnp.asarray(np.random.RandomState(2).randn(T, H).astype("float32"))
    layer = MoEMLP(hidden_size=H, ffn_hidden_size=F, num_experts=E,
                   top_k=1, dtype=jnp.float32)
    params = layer.init(jax.random.PRNGKey(0), x)

    def loss(p):
        y, aux, z = layer.apply(p, x)
        return jnp.sum(y * y) + 0.01 * aux + 1e-3 * z

    g = jax.grad(loss)(params)
    leaves = jax.tree.leaves(g)
    assert all(np.all(np.isfinite(np.asarray(l))) for l in leaves)
    # the router must receive gradient through the combine weights
    assert float(jnp.abs(g["params"]["router"]).sum()) > 0
    assert float(jnp.abs(g["params"]["w1"]).sum()) > 0


class TestExpertParallel:
    """ep=4 on the 8-device CPU mesh (dp=2 x ep=4)."""

    @pytest.fixture(autouse=True)
    def _mp(self):
        parallel_state.initialize_model_parallel(expert_model_parallel_size_=4)
        yield
        parallel_state.destroy_model_parallel()

    def test_parallel_state_ep(self):
        assert parallel_state.get_expert_model_parallel_world_size() == 4
        # full dense replica group = dp_raw * ep = 2 * 4 (pairs with
        # get_data_parallel_group); raw data axis = 2 (expert replicas)
        assert parallel_state.get_data_parallel_world_size() == 8
        assert parallel_state.get_expert_data_parallel_world_size() == 2
        assert parallel_state.get_data_parallel_group() == ("data", "expert")
        assert parallel_state.get_expert_data_parallel_group() == "data"
        mesh = parallel_state.get_mesh()
        assert mesh.shape == {"pipeline": 1, "data": 2, "expert": 4,
                              "tensor": 1}

    def test_ep_matches_assembled_single_device(self):
        """Each (data, expert) rank's MoE output equals the ep=1 layer
        run on that rank's tokens with the all-gathered expert stack."""
        T, H, F, E = 8, 8, 16, 8  # T per rank; e_local = 2
        layer = MoEMLP(hidden_size=H, ffn_hidden_size=F, num_experts=E,
                       top_k=2, capacity_factor=8.0, dtype=jnp.float32)
        rng = np.random.RandomState(3)
        xs = rng.randn(8 * T, H).astype("float32")  # 8 rank shards

        def f(x):
            params = layer.init(jax.random.PRNGKey(5), x)
            y, aux, z = layer.apply(params, x)
            # router is invarying (shared key); gathered expert stacks are
            # varying over "expert" only — pmean that axis to mark them
            # invariant (identical copies) for the replicated out_spec.
            full = {
                "router": params["params"]["router"],
                **{k: jax.lax.pmean(jax.lax.all_gather(
                       params["params"][k], "expert", axis=0, tiled=True),
                       "expert")
                   for k in ("w1", "b1", "w2", "b2")},
            }
            return y, full

        mesh = parallel_state.get_mesh()
        y, full = jax.jit(jax.shard_map(
            f, mesh=mesh,
            in_specs=P(("data", "expert")),
            out_specs=(P(("data", "expert")), P()),
        ))(jnp.asarray(xs))

        p = jax.tree.map(np.asarray, full)
        assert p["w1"].shape == (E, H, F)
        # experts must be decorrelated across ep ranks (rank-folded init)
        assert not np.allclose(p["w1"][0], p["w1"][2])
        cap = max(1, int(-(-2 * T * 8.0 // E)))
        for r in range(8):
            x_r = xs[r * T:(r + 1) * T]
            logits = x_r @ p["router"]
            _, combine = _np_route_top_k(logits, 2, cap)
            y_ref = _np_expert_mlp(x_r, combine, p["w1"], p["b1"],
                                   p["w2"], p["b2"])
            np.testing.assert_allclose(np.asarray(y)[r * T:(r + 1) * T],
                                       y_ref, rtol=1e-4, atol=1e-5)

    def test_ep_grads_finite_and_router_synced(self):
        """Grad flow through the all_to_all path; dense (router) grads
        psum'd over the full dp group stay finite."""
        T, H, F, E = 4, 4, 8, 4
        layer = MoEMLP(hidden_size=H, ffn_hidden_size=F, num_experts=E,
                       top_k=1, dtype=jnp.float32)
        xs = jnp.asarray(
            np.random.RandomState(4).randn(8 * T, H).astype("float32"))

        def f(x):
            params = layer.init(jax.random.PRNGKey(6), x)

            def loss(p):
                y, aux, z = layer.apply(p, x)
                return jnp.sum(y * y) + 0.01 * aux

            g = jax.grad(loss)(params)["params"]
            # dense-param grad sync: full dp group (data x expert)
            g_router = jax.lax.pmean(
                g["router"], parallel_state.get_data_parallel_group())
            # expert-param grad sync: data axis only
            g_w1 = jax.lax.pmean(
                g["w1"], parallel_state.get_expert_data_parallel_group())
            # g_w1 is already data-invariant after its pmean; only the
            # expert axis still varies on the scalar magnitude
            return g_router, jax.lax.pmean(jnp.sum(jnp.abs(g_w1)), "expert")

        mesh = parallel_state.get_mesh()
        g_router, g_w1_mag = jax.jit(jax.shard_map(
            f, mesh=mesh, in_specs=P(("data", "expert")),
            out_specs=(P(), P()),
        ))(xs)
        assert np.all(np.isfinite(np.asarray(g_router)))
        assert float(g_w1_mag) > 0


class TestTensorExpertParallel:
    """tp=2 x ep=2 x dp=2 on the 8-device CPU mesh: TPxEP grouped-GEMM
    experts must match the assembled (full-weight) per-token reference."""

    @pytest.fixture(autouse=True)
    def _mp(self):
        parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=2, expert_model_parallel_size_=2)
        yield
        parallel_state.destroy_model_parallel()

    def test_tp_ep_matches_assembled(self):
        T, H, F, E = 8, 8, 16, 4  # e_local=2, f_local=8
        layer = MoEMLP(hidden_size=H, ffn_hidden_size=F, num_experts=E,
                       top_k=2, capacity_factor=8.0, dtype=jnp.float32)
        rng = np.random.RandomState(7)
        xs = rng.randn(4 * T, H).astype("float32")  # (data x expert) shards

        def f(x):
            params = layer.init(jax.random.PRNGKey(9), x)
            y, aux, z = layer.apply(params, x)
            p = params["params"]
            # assemble: gather tp shards within each expert, then the
            # expert stacks over the ep axis
            w1 = jax.lax.all_gather(p["w1"], "tensor", axis=2, tiled=True)
            w2 = jax.lax.all_gather(p["w2"], "tensor", axis=1, tiled=True)
            b1 = jax.lax.all_gather(p["b1"], "tensor", axis=1, tiled=True)
            full = {
                "router": p["router"],
                "w1": jax.lax.pmean(jax.lax.all_gather(
                    jax.lax.pmean(w1, "tensor"), "expert", axis=0,
                    tiled=True), "expert"),
                "w2": jax.lax.pmean(jax.lax.all_gather(
                    jax.lax.pmean(w2, "tensor"), "expert", axis=0,
                    tiled=True), "expert"),
                "b1": jax.lax.pmean(jax.lax.all_gather(
                    jax.lax.pmean(b1, "tensor"), "expert", axis=0,
                    tiled=True), "expert"),
                "b2": jax.lax.pmean(jax.lax.all_gather(
                    p["b2"], "expert", axis=0, tiled=True), "expert"),
            }
            # y is tp-replicated; pmean marks it invariant for the spec
            return jax.lax.pmean(y, "tensor"), full

        mesh = parallel_state.get_mesh()
        y, full = jax.jit(jax.shard_map(
            f, mesh=mesh,
            in_specs=P(("data", "expert")),  # replicated over tensor
            out_specs=(P(("data", "expert")), P()),
        ))(jnp.asarray(xs))

        p = jax.tree.map(np.asarray, full)
        assert p["w1"].shape == (E, H, F)
        # tp shards of one expert assemble a full matrix; distinct experts
        # stay decorrelated across ep ranks
        assert not np.allclose(p["w1"][0], p["w1"][2])
        cap = max(1, int(-(-2 * T * 8.0 // E)))
        for r in range(4):
            x_r = xs[r * T:(r + 1) * T]
            logits = x_r @ p["router"]
            _, combine = _np_route_top_k(logits, 2, cap)
            y_ref = _np_expert_mlp(x_r, combine, p["w1"], p["b1"],
                                   p["w2"], p["b2"])
            np.testing.assert_allclose(np.asarray(y)[r * T:(r + 1) * T],
                                       y_ref, rtol=1e-4, atol=1e-5)


    @pytest.mark.slow
    def test_tp_ep_grads_match_assembled(self):
        """Backward through the TPxEP path: gathered per-shard w1 grads
        must equal jax.grad of a dense re-implementation on the
        assembled full weights (global loss = sum over all rank shards;
        shard cotangents arrive data-summed automatically and
        cross-source contributions flow back through the all_to_all)."""
        T, H, F, E = 8, 8, 16, 4
        layer = MoEMLP(hidden_size=H, ffn_hidden_size=F, num_experts=E,
                       top_k=2, capacity_factor=8.0, dtype=jnp.float32)
        rng = np.random.RandomState(11)
        xs = rng.randn(4 * T, H).astype("float32")
        cap = max(1, int(-(-2 * T * 8.0 // E)))

        def f(x):
            params = layer.init(jax.random.PRNGKey(13), x)

            def loss(p):
                y, aux, z = layer.apply(p, x)
                return jnp.sum(y * y)

            g = jax.grad(loss)(params)["params"]
            g1 = jax.lax.all_gather(g["w1"], "tensor", axis=2, tiled=True)
            g1 = jax.lax.pmean(jax.lax.all_gather(
                jax.lax.pmean(g1, "tensor"), "expert", axis=0, tiled=True),
                "expert")
            p = params["params"]
            w1 = jax.lax.all_gather(p["w1"], "tensor", axis=2, tiled=True)
            full = {
                "router": p["router"],
                "w1": jax.lax.pmean(jax.lax.all_gather(
                    jax.lax.pmean(w1, "tensor"), "expert", axis=0,
                    tiled=True), "expert"),
                "b1": jax.lax.pmean(jax.lax.all_gather(jax.lax.pmean(
                    jax.lax.all_gather(p["b1"], "tensor", axis=1,
                                       tiled=True), "tensor"),
                    "expert", axis=0, tiled=True), "expert"),
                "w2": jax.lax.pmean(jax.lax.all_gather(jax.lax.pmean(
                    jax.lax.all_gather(p["w2"], "tensor", axis=1,
                                       tiled=True), "tensor"),
                    "expert", axis=0, tiled=True), "expert"),
                "b2": jax.lax.pmean(jax.lax.all_gather(
                    p["b2"], "expert", axis=0, tiled=True), "expert"),
            }
            return jax.lax.pmean(g1, "data"), full

        mesh = parallel_state.get_mesh()
        g1_sharded, full = jax.jit(jax.shard_map(
            f, mesh=mesh, in_specs=P(("data", "expert")),
            out_specs=(P(), P()),
        ))(jnp.asarray(xs))
        p = jax.tree.map(jnp.asarray, full)

        def ref_loss(w1_full):
            total = 0.0
            for r in range(4):
                x_r = jnp.asarray(xs[r * T:(r + 1) * T])
                routing = route_top_k(x_r @ p["router"], 2, cap)
                slots = jnp.einsum("tec,th->ech", routing.dispatch, x_r)
                h = jax.nn.gelu(jnp.einsum("ech,ehf->ecf", slots, w1_full)
                                + p["b1"][:, None, :])
                out = (jnp.einsum("ecf,efh->ech", h, p["w2"])
                       + p["b2"][:, None, :])
                y = jnp.einsum("ech,tec->th", out, routing.combine)
                total = total + jnp.sum(y * y)
            return total

        g_ref = jax.grad(ref_loss)(p["w1"])
        # shard cotangents arrive data-summed (= the global-loss grad);
        # the pmean over identical summed copies is an identity
        np.testing.assert_allclose(np.asarray(g1_sharded),
                                   np.asarray(g_ref), rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_gpt_moe_block_end_to_end():
    """Tiny MoE-GPT: forward under remat, losses sown, grads finite."""
    from apex_tpu.models.gpt import (
        GPTConfig, GPTLMHeadModel, lm_loss, moe_losses_total,
    )

    cfg = GPTConfig.tiny(num_experts=4, moe_top_k=2, dropout=0.0,
                         fused_kernels=False, remat=True)
    model = GPTLMHeadModel(cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 16)))
    params = model.init(jax.random.PRNGKey(0), ids)

    def loss_fn(p):
        logits, col = model.apply(p, ids, mutable=("losses",))
        return lm_loss(logits, ids) + moe_losses_total(col)

    loss, g = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    flat = jax.tree.leaves(g)
    assert all(np.all(np.isfinite(np.asarray(l))) for l in flat)
    # expert weights exist and received gradient
    moe_g = g["params"]["transformer"]["h_0"]["moe_mlp"]["w1"]
    assert float(jnp.abs(moe_g).sum()) > 0
