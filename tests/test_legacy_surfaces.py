"""Legacy-surface tests: apex.reparameterization (weight norm) and
apex.RNN (upstream analog: their L0 unit tests; SURVEY.md §2.1)."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.reparameterization import (
    apply_weight_norm,
    compute_weights,
    remove_weight_norm,
    weight_norm,
)
from apex_tpu.RNN import GRU, LSTM, RNN, GRUCell, LSTMCell, RNNCell


# ---------------------------------------------------- reparameterization

def test_weight_norm_roundtrip_identity():
    """reparameterize then compute_weight reproduces the weight exactly."""
    params = {"dense": {"kernel": jnp.asarray(
        np.random.RandomState(0).randn(6, 4).astype("f4")),
        "bias": jnp.zeros((4,))}}
    wn = apply_weight_norm(params)
    assert set(wn["dense"].keys()) == {"kernel_g", "kernel_v", "bias"}
    back = compute_weights(wn)
    np.testing.assert_allclose(np.asarray(back["dense"]["kernel"]),
                               np.asarray(params["dense"]["kernel"]),
                               rtol=1e-6)
    # remove == compute
    removed = remove_weight_norm(wn)
    np.testing.assert_allclose(np.asarray(removed["dense"]["kernel"]),
                               np.asarray(params["dense"]["kernel"]),
                               rtol=1e-6)


def test_weight_norm_direction_invariance():
    """Scaling v leaves w unchanged (the property weight norm exists for:
    g alone controls the magnitude)."""
    v = jnp.asarray(np.random.RandomState(0).randn(6, 4).astype("f4"))
    g = jnp.ones((1, 4))
    w1 = weight_norm(v, g, dim=1)
    w2 = weight_norm(3.0 * v, g, dim=1)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w2), rtol=1e-5)
    # per-column norms equal g
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(w1), axis=0), 1.0, rtol=1e-5)


@pytest.mark.slow
def test_weight_norm_training_with_model():
    """Train the (g, v) parameterization end-to-end through a flax model."""
    model = nn.Dense(1, use_bias=False)
    x = jnp.asarray(np.random.RandomState(0).randn(32, 8).astype("f4"))
    y = x @ np.random.RandomState(1).randn(8, 1).astype("f4")
    params = model.init(jax.random.PRNGKey(0), x)["params"]
    wn_params = apply_weight_norm(params)

    def loss_fn(wn):
        w = compute_weights(wn)
        return jnp.mean((model.apply({"params": w}, x) - y) ** 2)

    losses = []
    for _ in range(60):
        l, g = jax.jit(jax.value_and_grad(loss_fn))(wn_params)
        wn_params = jax.tree.map(lambda p, gr: p - 0.1 * gr, wn_params, g)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.1


# ----------------------------------------------------------------- RNN

def _np_lstm_ref(x, p, H):
    """Numpy reference for one LSTM layer with the i,f,g,o layout."""
    T, B, _ = x.shape
    wih, bih = np.asarray(p["ih"]["kernel"]), np.asarray(p["ih"]["bias"])
    whh, bhh = np.asarray(p["hh"]["kernel"]), np.asarray(p["hh"]["bias"])
    h = np.zeros((B, H), "f4")
    c = np.zeros((B, H), "f4")
    outs = []

    def sig(a):
        return 1.0 / (1.0 + np.exp(-a))

    for t in range(T):
        gates = x[t] @ wih + bih + h @ whh + bhh
        i, f, g, o = np.split(gates, 4, axis=-1)
        c = sig(f) * c + sig(i) * np.tanh(g)
        h = sig(o) * np.tanh(c)
        outs.append(h)
    return np.stack(outs), h, c


def test_lstm_matches_numpy_reference():
    T, B, I, H = 5, 3, 4, 6
    model = LSTM(I, H)
    x = jnp.asarray(np.random.RandomState(0).randn(T, B, I).astype("f4"))
    variables = model.init(jax.random.PRNGKey(0), x)
    outs, carries = model.apply(variables, x)
    ref_outs, ref_h, ref_c = _np_lstm_ref(
        np.asarray(x), variables["params"]["layer_0"], H)
    np.testing.assert_allclose(np.asarray(outs), ref_outs, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(carries[0][0]), ref_h, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(carries[0][1]), ref_c, rtol=1e-4,
                               atol=1e-5)


@pytest.mark.parametrize("factory,cellname", [
    (RNN, "RNNCell"), (LSTM, "LSTMCell"), (GRU, "GRUCell")])
def test_stacked_rnn_shapes_and_grads(factory, cellname):
    T, B, I, H = 4, 2, 3, 5
    model = factory(I, H, num_layers=2)
    x = jnp.asarray(np.random.RandomState(0).randn(T, B, I).astype("f4"))
    variables = model.init(jax.random.PRNGKey(0), x)
    outs, carries = model.apply(variables, x)
    assert outs.shape == (T, B, H)
    assert len(carries) == 2
    g = jax.grad(lambda v: jnp.sum(model.apply(v, x)[0]))(variables)
    flat = jax.tree.leaves(g)
    assert all(np.isfinite(np.asarray(l)).all() for l in flat)
    assert any(float(jnp.max(jnp.abs(l))) > 0 for l in flat)


def test_rnn_nonlinearity_wiring():
    """relu cells produce non-negative outputs; tanh can go negative."""
    x = jnp.asarray(np.random.RandomState(0).randn(6, 2, 3).astype("f4"))
    relu_net = RNN(3, 5, nonlinearity="relu")
    v = relu_net.init(jax.random.PRNGKey(0), x)
    outs, _ = relu_net.apply(v, x)
    assert float(jnp.min(outs)) >= 0.0
    with pytest.raises(ValueError):
        RNN(3, 5, nonlinearity="selu")


def test_rnn_sequence_memory():
    """An LSTM can carry information across the sequence: output at the
    last step depends on the first input."""
    model = LSTM(2, 8)
    x = jnp.zeros((6, 1, 2))
    variables = model.init(jax.random.PRNGKey(0), x)
    out_zero, _ = model.apply(variables, x)
    x2 = x.at[0, 0, 0].set(5.0)
    out_mod, _ = model.apply(variables, x2)
    assert float(jnp.max(jnp.abs(out_zero[-1] - out_mod[-1]))) > 1e-4


def test_initial_carries_roundtrip():
    """Feeding the final carries back continues the sequence exactly."""
    model = GRU(3, 4)
    x = jnp.asarray(np.random.RandomState(0).randn(8, 2, 3).astype("f4"))
    variables = model.init(jax.random.PRNGKey(0), x)
    full_out, _ = model.apply(variables, x)
    first_out, carries = model.apply(variables, x[:4])
    second_out, _ = model.apply(variables, x[4:],
                                initial_carries=carries)
    np.testing.assert_allclose(np.asarray(second_out),
                               np.asarray(full_out[4:]), rtol=1e-5,
                               atol=1e-6)
