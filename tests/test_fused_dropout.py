"""Fused (hardware-PRNG) dropout: determinism, statistics, and the
mask-replay backward (component: ops/dropout.py — the reference's
fused Philox dropout epilogues, apex/contrib/csrc/multihead_attn (U))."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.dropout import fused_dropout


def test_zero_rate_is_identity():
    x = jnp.arange(12.0).reshape(3, 4)
    np.testing.assert_array_equal(np.asarray(fused_dropout(x, 0.0)),
                                  np.asarray(x))


def test_requires_seed():
    with pytest.raises(ValueError, match="seed"):
        fused_dropout(jnp.ones((4, 4)), 0.1, None)


@pytest.mark.parametrize("shape", [(16, 512, 1024), (3, 7, 11), (100,)])
@pytest.mark.slow
def test_statistics_and_determinism(shape):
    x = jnp.ones(shape, jnp.float32)
    rate = 0.1
    y1 = jax.jit(lambda x: fused_dropout(x, rate, 5))(x)
    y2 = jax.jit(lambda x: fused_dropout(x, rate, 5))(x)
    y3 = jax.jit(lambda x: fused_dropout(x, rate, 6))(x)
    a1 = np.asarray(y1)
    assert (a1 == np.asarray(y2)).all()          # same seed: identical
    if a1.size >= 1000:
        assert (a1 != np.asarray(y3)).any()      # new seed: new mask
        kept = (a1 != 0).mean()
        assert abs(kept - (1 - rate)) < 0.02
    # kept values are exactly x / keep
    np.testing.assert_allclose(a1[a1 != 0], 1.0 / (1 - rate), rtol=1e-6)


def test_backward_replays_identical_mask():
    """grad must be g * mask / keep with the FORWARD's mask: for
    y = dropout(x) and loss = sum(y * w), dx = dropout(w) with the same
    seed — and kept positions of y and dx must coincide."""
    x = jnp.asarray(np.random.RandomState(0).randn(64, 256).astype("f4"))
    w = jnp.asarray(np.random.RandomState(1).randn(64, 256).astype("f4"))
    rate, seed = 0.2, 99

    def loss(x):
        return jnp.sum(fused_dropout(x, rate, seed) * w)

    y = jax.jit(lambda x: fused_dropout(x, rate, seed))(x)
    dx = jax.jit(jax.grad(loss))(x)
    ay, adx = np.asarray(y), np.asarray(dx)
    np.testing.assert_array_equal(ay != 0, adx != 0)
    keep = ay != 0
    np.testing.assert_allclose(adx[keep],
                               (np.asarray(w) / (1 - rate))[keep],
                               rtol=1e-5)


@pytest.mark.slow
def test_bert_layer_trains_with_fused_dropout():
    """End-to-end: a training step through the BERT layer with fused
    hidden+attention dropout produces finite loss and grads."""
    from apex_tpu.models import BertConfig, BertForPreTraining, pretraining_loss

    cfg = BertConfig.tiny(hidden_dropout=0.1, attention_dropout=0.1)
    model = BertForPreTraining(cfg)
    rng = np.random.RandomState(0)
    B, S = 2, 32
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)))
    params = model.init(jax.random.PRNGKey(0), ids)["params"]

    def loss_fn(p, key):
        mlm, nsp = model.apply({"params": p}, ids, deterministic=False,
                               rngs={"dropout": key})
        labels = jnp.where(jnp.arange(S)[None] % 7 == 0, ids, -1)
        return pretraining_loss(mlm, nsp, labels,
                                jnp.zeros((B,), jnp.int32))

    loss, g = jax.jit(jax.value_and_grad(loss_fn))(params,
                                                   jax.random.PRNGKey(1))
    assert np.isfinite(float(loss))
    assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(g))
