"""apex_tpu.data tests: native C hot path vs contracts (shapes, masking
ratios, determinism, epoch reshuffle, prefetch ordering)."""

import numpy as np
import pytest

from apex_tpu.data import CausalLMBatchLoader, MLMBatchLoader, native_available
from apex_tpu.data.loader import _gather_rows, _mlm_mask, _shuffled_indices


def test_native_builds():
    # the toolchain in CI has cc; if this fails the numpy fallback is
    # covering everything, which the other tests would still validate
    assert native_available()


def test_shuffle_is_permutation_and_deterministic():
    a = _shuffled_indices(1000, seed=42)
    b = _shuffled_indices(1000, seed=42)
    c = _shuffled_indices(1000, seed=43)
    np.testing.assert_array_equal(np.sort(a), np.arange(1000))
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)
    assert not np.array_equal(a, np.arange(1000))  # actually shuffled


def test_gather_rows_matches_numpy():
    corpus = np.arange(50 * 7, dtype=np.int32).reshape(50, 7)
    idx = np.asarray([3, 0, 49, 17], np.uint64)
    np.testing.assert_array_equal(_gather_rows(corpus, idx),
                                  corpus[idx.astype(int)])


def test_mlm_mask_contract():
    rng = np.random.RandomState(0)
    vocab, mask_id = 1000, 4
    special = np.asarray([0, 1, 2, 3, 4], np.int32)
    tokens = rng.randint(5, vocab, (64, 128)).astype(np.int32)
    tokens[:, 0] = 1   # [CLS]-like
    tokens[:, -1] = 2  # [SEP]-like
    ids, labels = _mlm_mask(tokens, vocab, mask_id, special, 0.15, seed=7)

    # unmasked positions: ids unchanged, label -1
    un = labels == -1
    np.testing.assert_array_equal(ids[un], tokens[un])
    # masked positions: label holds the original token
    np.testing.assert_array_equal(labels[~un], tokens[~un])
    # special positions are never selected
    assert (labels[:, 0] == -1).all() and (labels[:, -1] == -1).all()
    # selection rate ~15%
    frac = (~un).mean() * 128 / 126  # exclude the 2 special slots
    assert 0.12 < frac < 0.18, frac
    # of selected: ~80% [MASK], ~10% random, ~10% unchanged
    sel_ids, sel_orig = ids[~un], tokens[~un]
    m = (sel_ids == mask_id).mean()
    keep = (sel_ids == sel_orig).mean()
    assert 0.7 < m < 0.9, m
    assert 0.05 < keep < 0.17, keep
    # deterministic per seed
    ids2, labels2 = _mlm_mask(tokens, vocab, mask_id, special, 0.15, seed=7)
    np.testing.assert_array_equal(ids, ids2)
    np.testing.assert_array_equal(labels, labels2)
    ids3, _ = _mlm_mask(tokens, vocab, mask_id, special, 0.15, seed=8)
    assert not np.array_equal(ids, ids3)


def test_mlm_loader_epochs_and_shapes():
    rng = np.random.RandomState(1)
    corpus = rng.randint(5, 500, (40, 16)).astype(np.int32)
    loader = MLMBatchLoader(corpus, batch_size=8, vocab_size=500, mask_id=3,
                            special_ids=[0, 1, 2, 3], seed=5)
    assert len(loader) == 5
    batches = list(loader)
    assert len(batches) == 5
    for ids, labels in batches:
        assert ids.shape == (8, 16) and ids.dtype == np.int32
        assert labels.shape == (8, 16)
    # same epoch re-iterated: identical stream (reproducibility)
    again = list(loader)
    for (a, la), (b, lb) in zip(batches, again):
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(la, lb)
    # new epoch: different shuffle
    loader.set_epoch(1)
    third = list(loader)
    assert any(not np.array_equal(a, b)
               for (a, _), (b, _) in zip(batches, third))
    # every corpus row appears exactly once per epoch (modulo masking):
    # collect unmasked positions to reconstruct rows is overkill — check
    # the row multiset via label-restored tokens
    restored = np.concatenate(
        [np.where(l == -1, i, l) for i, l in third])  # (40, 16)
    assert (np.sort(restored.sum(1)) == np.sort(corpus.sum(1))).all()


def test_causal_loader_covers_corpus():
    corpus = np.arange(12 * 4, dtype=np.int32).reshape(12, 4)
    loader = CausalLMBatchLoader(corpus, batch_size=4, seed=9)
    got = np.concatenate(list(loader))
    assert got.shape == (12, 4)
    np.testing.assert_array_equal(
        np.sort(got.reshape(-1)), np.sort(corpus.reshape(-1)))


def test_drop_last_true_drops_tail():
    corpus = np.zeros((10, 4), np.int32)
    loader = CausalLMBatchLoader(corpus, batch_size=3)  # drop_last
    assert len(loader) == 3
    assert all(b.shape == (3, 4) for b in loader)


def test_drop_last_false_pads_and_masks_tail():
    """torch-DataLoader parity with static shapes: the epoch tail is
    padded to batch_size and masked via per-sample weights."""
    corpus = np.arange(10 * 4, dtype=np.int32).reshape(10, 4)
    loader = CausalLMBatchLoader(corpus, batch_size=3, drop_last=False,
                                 shuffle=False, seed=9)
    assert len(loader) == 4
    assert [loader.valid_rows(b) for b in range(4)] == [3, 3, 3, 1]
    batches = list(loader)
    assert len(batches) == 4
    for ids, weights in batches:  # static shapes incl. the tail
        assert ids.shape == (3, 4) and weights.shape == (3,)
    full_w = np.concatenate([w for _, w in batches])
    assert full_w.tolist() == [1.0] * 9 + [1.0, 0.0, 0.0]
    # valid rows cover the whole corpus exactly once
    got = np.concatenate([ids[w == 1.0] for ids, w in batches])
    np.testing.assert_array_equal(np.sort(got, axis=0), corpus)
    with pytest.raises(IndexError):
        loader.valid_rows(4)


def test_drop_last_false_mlm_tail_labels():
    """MLM padding rows must carry -1 labels (zero loss) and weight 0."""
    rng = np.random.RandomState(3)
    corpus = rng.randint(5, 500, (11, 8)).astype(np.int32)
    loader = MLMBatchLoader(corpus, batch_size=4, vocab_size=500,
                            mask_id=3, special_ids=[0, 1, 2, 3],
                            drop_last=False, seed=5)
    assert len(loader) == 3
    batches = list(loader)
    ids, labels, weights = batches[-1]
    assert ids.shape == (4, 8) and weights.tolist() == [1, 1, 1, 0]
    assert (labels[weights == 0.0] == -1).all()
    # non-tail batches still carry (all-ones) weights: static pytree
    # structure across the epoch
    assert all(len(b) == 3 and b[2].all() for b in batches[:-1])


def test_prefetch_propagates_worker_exceptions():
    """A batch-assembly error must crash the consumer, not hang it."""
    from apex_tpu.data.loader import _PrefetchIterator

    def bad_batch(i):
        if i == 2:
            raise RuntimeError("corrupt shard")
        return i

    it = _PrefetchIterator(bad_batch, n_batches=5, depth=1)
    got = []
    with pytest.raises(RuntimeError, match="corrupt shard"):
        for x in it:
            got.append(x)
    assert got == [0, 1]


def test_prefetch_early_abandon_releases_worker():
    """Breaking out of iteration must not strand the worker thread."""
    from apex_tpu.data.loader import _PrefetchIterator

    it = _PrefetchIterator(lambda i: i, n_batches=1000, depth=1)
    assert next(it) == 0
    thread = it._thread
    it.close()
    thread.join(timeout=5.0)
    assert not thread.is_alive()
