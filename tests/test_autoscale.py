"""Elastic autoscaler certification (tier-1, CPU): the ISSUE 16
control loop in :class:`~apex_tpu.serving.fleet.FleetRouter`
(docs/fleet.md, "Autoscaler").

The contract under test: spawn only after a SUSTAINED high-watermark
breach (the consecutive-tick patience debounce — a one-tick spike
never scales), retire through the clean drain-and-migrate path on a
sustained low-watermark, never cross ``autoscale_min_replicas`` /
``autoscale_max_replicas`` (the bounds gate the STREAKS, so a fleet
pinned at a bound holds no primed trigger), no flapping at steady
state, spawn/retire surfaced in ``stats()``
(``num_spawned``/``num_retired``) and the flight recorder
(``replica_spawn``/``replica_retire`` + the trace_summary autoscaler
line) — and the never-firing identity cert: a fleet with ±inf
watermarks runs BIT-IDENTICAL to a static fleet (outputs, statuses,
full stats), because the armed-but-idle control loop is pure
``load()`` reads."""

import importlib.util
import json
import math
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models import GPTConfig, GPTLMHeadModel
from apex_tpu.observability import Observability
from apex_tpu.serving import (
    EngineConfig,
    FleetConfig,
    FleetRouter,
    Request,
    SamplingParams,
)

ENGINE_KW = dict(max_batch=1, block_size=4, num_blocks=64,
                 max_prefill_len=8, max_seq_len=48, seed=7,
                 enable_prefix_caching=True)


@pytest.fixture(scope="module")
def tiny_gpt():
    cfg = GPTConfig.tiny(dropout=0.0, remat=False)
    model = GPTLMHeadModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    return model, params


def _fleet(tiny_gpt, n=1, fleet_kw=None, obs=None, clock=None,
           **overrides):
    model, params = tiny_gpt
    kw = dict(ENGINE_KW)
    kw.update(overrides)
    return FleetRouter(model, params, EngineConfig(**kw),
                       FleetConfig(num_replicas=n, **(fleet_kw or {})),
                       obs=obs, clock=clock)


def _reqs(n, new=8, seed=3, uid="a"):
    rng = np.random.RandomState(seed)
    return [Request(f"{uid}{k}", list(rng.randint(1, 50, 6)),
                    max_new_tokens=new, sampling=SamplingParams())
            for k in range(n)]


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------


def test_autoscale_config_validation():
    FleetConfig(autoscale_high_watermark=4.0,
                autoscale_low_watermark=1.0)    # legal
    with pytest.raises(ValueError, match="autoscale_high_watermark"):
        FleetConfig(autoscale_high_watermark=1.0,
                    autoscale_low_watermark=2.0)
    with pytest.raises(ValueError, match="autoscale_patience"):
        FleetConfig(autoscale_patience=0)
    with pytest.raises(ValueError, match="autoscale_min_replicas"):
        FleetConfig(autoscale_min_replicas=0)
    with pytest.raises(ValueError, match="autoscale_max_replicas"):
        FleetConfig(autoscale_min_replicas=3, autoscale_max_replicas=2)


# ---------------------------------------------------------------------------
# spawn / retire mechanics
# ---------------------------------------------------------------------------


def test_autoscale_spawns_only_after_patience(tiny_gpt):
    fleet = _fleet(tiny_gpt, fleet_kw=dict(
        autoscale_high_watermark=1.0, autoscale_patience=3,
        autoscale_max_replicas=2))
    for r in _reqs(8):
        fleet.add_request(r)
    # the breach must SUSTAIN through `patience` consecutive ticks
    for tick in range(2):
        fleet.step()
        assert len(fleet.replicas) == 1, \
            f"spawned after only {tick + 1} tick(s) of patience 3"
    fleet.step()
    assert len(fleet.replicas) == 2
    assert fleet.stats()["num_spawned"] == 1
    assert fleet.replicas[1].mode == "in_process"
    fleet.run()
    assert fleet.stats()["num_lost_requests"] == 0


def test_autoscale_grows_and_shrinks_within_bounds(tiny_gpt):
    obs = Observability(trace=False, metrics=False)
    fleet = _fleet(tiny_gpt, obs=obs, fleet_kw=dict(
        autoscale_high_watermark=1.0, autoscale_low_watermark=0.5,
        autoscale_patience=2, autoscale_max_replicas=3))
    for r in _reqs(10, new=16):
        fleet.add_request(r)
    sizes = []
    while fleet.has_work:
        fleet.step()
        sizes.append(len(fleet._alive()))
    st = fleet.stats()
    assert max(sizes) <= 3                      # max bound held
    assert min(sizes) >= 1                      # min bound held
    assert max(sizes) > 1, "the burst never triggered a spawn"
    assert sizes[-1] == 1, "the drained fleet did not shrink to min"
    assert st["num_spawned"] >= 1 and st["num_retired"] >= 1
    assert st["num_spawned"] - st["num_retired"] == 0
    assert st["num_lost_requests"] == 0
    assert len(fleet.run()) == 10               # every uid terminal
    # steady state: an idle fleet at min size never flaps
    before = (st["num_spawned"], st["num_retired"])
    for _ in range(8):
        fleet.step()
    after = fleet.stats()
    assert (after["num_spawned"], after["num_retired"]) == before
    # recorder: every resize left its event
    kinds = [e["kind"] for e in obs.recorder.tail()]
    assert kinds.count("replica_spawn") == after["num_spawned"]
    assert kinds.count("replica_retire") == after["num_retired"]


def test_autoscale_bound_gates_the_streak(tiny_gpt):
    """At max size with a still-breached watermark, the hi streak
    stays DISARMED (not merely the action suppressed) — so the moment
    capacity frees up the fleet does not instantly fire a stale
    trigger."""
    fleet = _fleet(tiny_gpt, fleet_kw=dict(
        autoscale_high_watermark=0.5, autoscale_patience=2,
        autoscale_max_replicas=2))
    for r in _reqs(8, new=12):
        fleet.add_request(r)
    for _ in range(6):
        fleet.step()
    assert len(fleet.replicas) == 2             # pinned at max
    assert fleet._autoscale_hi_streak == 0      # …with no primed trigger
    fleet.run()
    assert fleet.stats()["num_lost_requests"] == 0


def test_autoscale_retire_uses_drain_and_migrate(tiny_gpt):
    """Scale-down retires through drain_replica(retire=True): the
    victim's live requests migrate to survivors, nothing is lost, and
    the retired slot reads dead in stats."""
    obs = Observability(trace=False, metrics=False)
    fleet = _fleet(tiny_gpt, n=2, obs=obs, fleet_kw=dict(
        autoscale_low_watermark=5.0, autoscale_patience=1,
        autoscale_min_replicas=1))
    for r in _reqs(3, new=10):
        fleet.add_request(r)
    fleet.step()                                # lo breached -> retire
    st = fleet.stats()
    assert st["num_retired"] == 1 and st["replicas_alive"] == 1
    res = fleet.run(return_status=True)
    assert sorted(res) == ["a0", "a1", "a2"]
    assert fleet.stats()["num_lost_requests"] == 0
    retire = [e for e in obs.recorder.tail()
              if e["kind"] == "replica_retire"]
    assert len(retire) == 1 and retire[0]["reason"] == "autoscale"


def test_autoscale_trace_summary_line(tiny_gpt, tmp_path):
    obs = Observability(trace=False, metrics=False)
    fleet = _fleet(tiny_gpt, obs=obs, fleet_kw=dict(
        autoscale_high_watermark=1.0, autoscale_low_watermark=0.5,
        autoscale_patience=2, autoscale_max_replicas=2))
    for r in _reqs(6, new=12):
        fleet.add_request(r)
    fleet.run()
    dump_path = tmp_path / "autoscale_dump.json"
    dump_path.write_text(json.dumps(obs.dump(), default=str))
    spec = importlib.util.spec_from_file_location(
        "_trace_summary",
        Path(__file__).resolve().parents[1] / "tools" /
        "trace_summary.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    report = mod.summarize_file(str(dump_path))
    assert "-- autoscaler:" in report
    assert "spawns" in report and "retires" in report


# ---------------------------------------------------------------------------
# the never-firing identity cert
# ---------------------------------------------------------------------------


def _run_fleet(tiny_gpt, fleet_kw):
    fleet = _fleet(tiny_gpt, n=2, fleet_kw=fleet_kw, clock=lambda: 0.0,
                   max_batch=2)
    for r in _reqs(6, new=6):
        fleet.add_request(r)
    res = fleet.run(return_status=True)
    return ({u: (tuple(r.tokens), r.status) for u, r in res.items()},
            json.loads(json.dumps(fleet.stats(), sort_keys=True,
                                  default=str)))


def test_never_firing_autoscaler_is_bit_identical(tiny_gpt):
    """Watermarks at ±inf arm the control loop on every tick but can
    never fire it; the loop is pure load() reads, so EVERYTHING — the
    outputs, the statuses, the full constant-clock stats() — matches
    the static fleet bit for bit."""
    static = _run_fleet(tiny_gpt, None)
    armed = _run_fleet(tiny_gpt, dict(
        autoscale_high_watermark=math.inf,
        autoscale_low_watermark=-math.inf,
        autoscale_patience=1, autoscale_max_replicas=8))
    assert armed[0] == static[0]
    assert armed[1] == static[1]
