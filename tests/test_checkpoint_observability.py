"""Checkpoint round-trip, per-step metrics, host overflow line, and the
same-seed determinism regression (SURVEY.md §5 auxiliary subsystems;
VERDICT round-1 item 9)."""

import io
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.amp import LossScaler
from apex_tpu.optimizers import FusedAdam
from apex_tpu.utils.checkpoint import (
    latest_step,
    load_checkpoint,
    save_checkpoint,
)


def _train_state():
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(4, 4).astype("float32")),
              "b": jnp.asarray(rng.randn(4).astype("float32"))}
    opt = FusedAdam(lr=1e-2)
    state = opt.init(params)
    # advance a step so moments are nonzero
    grads = jax.tree.map(jnp.ones_like, params)
    params, state = opt.step(grads, state, params)
    scaler = LossScaler("dynamic")
    sstate = scaler.init()
    return params, opt, state, scaler, sstate


def test_checkpoint_roundtrip_bitwise(tmp_path):
    params, opt, state, scaler, sstate = _train_state()
    d = str(tmp_path / "ckpt")
    save_checkpoint(d, 7, params=params, opt_state=state,
                    scaler_state=sstate)
    assert latest_step(d) == 7

    restored = load_checkpoint(
        d, template=dict(params=params, opt_state=state,
                         scaler_state=sstate))
    assert restored["_step"] == 7
    for a, b in zip(jax.tree.leaves(restored["params"]),
                    jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # NamedTuple containers restored via template
    assert type(restored["opt_state"]).__name__ == "AdamState"
    assert int(restored["opt_state"].step) == 1
    for a, b in zip(jax.tree.leaves(restored["opt_state"]),
                    jax.tree.leaves(state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert float(restored["scaler_state"].loss_scale) == 2.0 ** 16

    # resume: stepping from the restored state matches stepping the live one
    grads = jax.tree.map(jnp.ones_like, params)
    p1, s1 = opt.step(grads, restored["opt_state"], restored["params"])
    p2, s2 = opt.step(grads, state, params)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_latest_and_missing(tmp_path):
    d = str(tmp_path / "ckpt")
    assert latest_step(d) is None
    with pytest.raises(FileNotFoundError):
        load_checkpoint(d)
    params = {"w": jnp.ones((2,))}
    save_checkpoint(d, 1, params=params)
    save_checkpoint(d, 5, params=jax.tree.map(lambda x: x * 5, params))
    assert latest_step(d) == 5
    got = load_checkpoint(d)
    np.testing.assert_array_equal(np.asarray(got["params"]["w"]),
                                  [5.0, 5.0])


def test_metrics_dict():
    scaler = LossScaler("dynamic")
    st = scaler.init()
    m = LossScaler.metrics(st, grad_norm=jnp.float32(3.5),
                           loss=jnp.float32(1.25))
    assert set(m) == {"loss_scale", "unskipped", "steps_skipped",
                      "grad_norm", "loss"}
    assert float(m["loss_scale"]) == 2.0 ** 16
    assert float(m["grad_norm"]) == 3.5


def test_host_overflow_report_prints_contract_line(capsys):
    from apex_tpu.amp import set_ingraph_logging, set_verbosity
    from apex_tpu.amp._amp_state import get_verbosity

    # earlier tests may have initialized amp with verbosity=0
    prev_verbosity = get_verbosity()
    set_verbosity(1)
    # simulate a callback-less runtime (axon): host fallback must print
    set_ingraph_logging(False)
    try:
        scaler = LossScaler("dynamic")
        st = scaler.init()
        bad = {"g": jnp.asarray([jnp.inf, 1.0])}
        _, found = scaler.unscale(bad, st)
        st2 = scaler.update(st, found)

        skipped = scaler.host_overflow_report(st, st2)
        assert skipped
        out = capsys.readouterr().out  # stdout, where scripts grep
        assert ("Gradient overflow.  Skipping step, loss scaler 0 "
                "reducing loss scale to 32768.0") in out

        # clean step: no line
        good = {"g": jnp.asarray([1.0, 1.0])}
        _, found = scaler.unscale(good, st2)
        st3 = scaler.update(st2, found)
        assert not scaler.host_overflow_report(st2, st3)
    finally:
        set_ingraph_logging(None)
        set_verbosity(prev_verbosity)


def test_no_double_overflow_line_when_ingraph_active(capsys):
    """On callback-capable runtimes the in-graph path prints the line;
    the host fallback must then NOT print it again (grep-and-count)."""
    from apex_tpu.amp import set_ingraph_logging, set_verbosity
    from apex_tpu.amp._amp_state import get_verbosity

    prev_verbosity = get_verbosity()
    set_verbosity(1)
    set_ingraph_logging(True)
    try:
        scaler = LossScaler("dynamic")
        st = scaler.init()
        bad = {"g": jnp.asarray([jnp.inf, 1.0])}
        _, found = scaler.unscale(bad, st)
        st2 = scaler.update(st, found)
        jax.effects_barrier()
        assert scaler.host_overflow_report(st, st2)  # True, but no print
        out = capsys.readouterr().out
        assert out.count("Gradient overflow.") == 1
    finally:
        set_ingraph_logging(None)
        set_verbosity(prev_verbosity)


@pytest.mark.slow
def test_same_seed_bitwise_determinism():
    """SURVEY.md §5 race/determinism row: two runs from the same seed are
    bitwise identical — params, losses, and dropout behavior included."""
    from apex_tpu.models import BertConfig, BertForPreTraining
    from apex_tpu.models.bert import pretraining_loss

    def run():
        cfg = BertConfig.tiny(hidden_dropout=0.1, attention_dropout=0.1)
        model = BertForPreTraining(cfg)
        rng = np.random.RandomState(42)
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (2, 16)))
        labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (2, 16)))
        nsp = jnp.asarray(rng.randint(0, 2, (2,)))
        params = model.init(
            {"params": jax.random.PRNGKey(0),
             "dropout": jax.random.PRNGKey(1)}, ids, None, None)
        opt = FusedAdam(lr=1e-3)
        state = opt.init(params)

        @jax.jit
        def step(params, state, key):
            def loss_fn(p):
                mlm, nspl = model.apply(p, ids, None, None,
                                        deterministic=False,
                                        rngs={"dropout": key})
                return pretraining_loss(mlm, nspl, labels, nsp)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            params2, state2 = opt.step(grads, state, params)
            return params2, state2, loss

        losses = []
        for i in range(3):
            params, state, loss = step(params, state,
                                       jax.random.PRNGKey(100 + i))
            losses.append(np.asarray(loss))
        return losses, params

    l1, p1 = run()
    l2, p2 = run()
    for a, b in zip(l1, l2):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_qkv_checkpoint_remap_roundtrip():
    """Layout portability between the TP fused-qkv attention params and
    the non-TP split q/k/v layout (advisor r4): split/merge are exact
    inverses and preserve the Megatron [q | k | v] output-axis order."""
    import numpy as np

    from apex_tpu.utils.checkpoint import merge_split_qkv, split_fused_qkv

    rng = np.random.RandomState(0)
    kq, kk, kv = (rng.randn(8, 8).astype("f4") for _ in range(3))
    fused = {
        "layer_0": {
            "qkv": {"kernel": np.concatenate([kq, kk, kv], axis=-1),
                    "bias": np.arange(24, dtype="f4")},
            "out": {"kernel": rng.randn(8, 8).astype("f4")},
        },
        "layer_1": {
            "attn_qkv": {"kernel": np.concatenate([kq, kk, kv], axis=-1)},
        },
    }
    split = split_fused_qkv(fused)
    np.testing.assert_array_equal(split["layer_0"]["q"]["kernel"], kq)
    np.testing.assert_array_equal(split["layer_0"]["k"]["kernel"], kk)
    np.testing.assert_array_equal(split["layer_0"]["v"]["kernel"], kv)
    np.testing.assert_array_equal(split["layer_0"]["q"]["bias"],
                                  np.arange(8, dtype="f4"))
    assert "qkv" not in split["layer_0"]
    # untouched siblings pass through
    np.testing.assert_array_equal(split["layer_0"]["out"]["kernel"],
                                  fused["layer_0"]["out"]["kernel"])
    # GPT naming handled by the default map
    np.testing.assert_array_equal(split["layer_1"]["attn_q"]["kernel"], kq)

    merged = merge_split_qkv(split)
    jax.tree.map(np.testing.assert_array_equal, merged, fused)


def test_qkv_remap_projection_equivalence():
    """The remapped layouts compute the SAME attention projections: a
    fused qkv matmul + 3-way split equals the three split projections."""
    import numpy as np

    from apex_tpu.utils.checkpoint import split_fused_qkv

    rng = np.random.RandomState(1)
    Wqkv = rng.randn(6, 18).astype("f4")
    x = rng.randn(4, 6).astype("f4")
    split = split_fused_qkv({"qkv": {"kernel": Wqkv}})
    q_f, k_f, v_f = np.split(x @ Wqkv, 3, axis=-1)
    np.testing.assert_allclose(x @ split["q"]["kernel"], q_f, rtol=1e-6)
    np.testing.assert_allclose(x @ split["k"]["kernel"], k_f, rtol=1e-6)
    np.testing.assert_allclose(x @ split["v"]["kernel"], v_f, rtol=1e-6)
