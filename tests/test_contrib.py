"""Contrib-tier tests (upstream analog: ``apex/contrib/test/*`` —
per-subpackage fused-vs-composed parity; SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.contrib.bottleneck import (
    Bottleneck,
    HaloExchanger1d,
    SpatialBottleneck,
)
from apex_tpu.contrib.focal_loss import focal_loss
from apex_tpu.contrib.group_norm import GroupNorm, group_norm_nhwc
from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC
from apex_tpu.contrib.index_mul_2d import index_mul_2d
from apex_tpu.contrib.multihead_attn import (
    EncdecMultiheadAttn,
    SelfMultiheadAttn,
)
from apex_tpu.contrib.sparsity import (
    ASP,
    MaskedOptimizer,
    apply_masks,
    compute_sparse_masks,
    m4n2_1d_mask,
    sparsity_ratio,
)


# ------------------------------------------------------ multihead_attn

def _mha_ref(q_in, p, nh, key_mask=None):
    """Composed reference for SelfMultiheadAttn (no dropout)."""
    T, B, H = q_in.shape
    hd = H // nh
    qkv = q_in @ p["qkv_proj"]["kernel"]
    q, k, v = np.split(np.asarray(qkv), 3, axis=-1)

    def heads(t):
        return t.reshape(T, B, nh, hd).transpose(1, 2, 0, 3)

    q, k, v = heads(q), heads(k), heads(v)
    s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(hd)
    if key_mask is not None:
        s = np.where(np.asarray(key_mask)[:, None, None, :], -30000.0, s)
    p_att = np.exp(s - s.max(-1, keepdims=True))
    p_att = p_att / p_att.sum(-1, keepdims=True)
    ctx = np.einsum("bhqk,bhkd->bhqd", p_att, v)
    ctx = ctx.transpose(2, 0, 1, 3).reshape(T, B, H)
    return ctx @ np.asarray(p["out_proj"]["kernel"])


@pytest.mark.parametrize("use_mask", [False, True])
def test_self_multihead_attn_matches_composed(use_mask):
    T, B, H, nh = 384, 2, 64, 4  # T >= flash path's block tiling
    attn = SelfMultiheadAttn(H, nh, dropout=0.0)
    x = jnp.asarray(np.random.RandomState(0).randn(T, B, H)
                    .astype("float32"))
    km = (jnp.asarray(np.random.RandomState(1).rand(B, T) < 0.2)
          if use_mask else None)
    params = attn.init(jax.random.PRNGKey(0), x, km, False)
    out = attn.apply(params, x, km, False)
    ref = _mha_ref(x, params["params"], nh, km)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-3, atol=2e-4)


@pytest.mark.slow
def test_self_multihead_attn_norm_add_and_dropout_path():
    T, B, H = 8, 2, 32
    attn = SelfMultiheadAttn(H, 4, dropout=0.5, include_norm_add=True)
    x = jnp.ones((T, B, H))
    params = attn.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        x, None, True)
    out = attn.apply(params, x, None, True,
                     rngs={"dropout": jax.random.PRNGKey(2)})
    assert out.shape == (T, B, H)
    assert np.isfinite(np.asarray(out)).all()
    # eval: deterministic, no dropout rng needed
    out2 = attn.apply(params, x, None, False)
    out3 = attn.apply(params, x, None, False)
    np.testing.assert_array_equal(np.asarray(out2), np.asarray(out3))


@pytest.mark.slow
def test_encdec_multihead_attn_shapes_and_grad():
    Tq, Tk, B, H = 6, 10, 2, 32
    attn = EncdecMultiheadAttn(H, 4, dropout=0.0)
    q = jnp.asarray(np.random.RandomState(0).randn(Tq, B, H).astype("f4"))
    k = jnp.asarray(np.random.RandomState(1).randn(Tk, B, H).astype("f4"))
    params = attn.init(jax.random.PRNGKey(0), q, k, None, False)
    out = attn.apply(params, q, k, None, False)
    assert out.shape == (Tq, B, H)
    g = jax.grad(lambda p: jnp.sum(attn.apply(p, q, k, None, False)))(params)
    assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(g))


# ---------------------------------------------------------- group_norm

def test_group_norm_matches_composed():
    x = jnp.asarray(np.random.RandomState(0).randn(2, 4, 4, 32)
                    .astype("float32"))
    gn = GroupNorm(num_groups=8, num_channels=32, act="silu")
    params = gn.init(jax.random.PRNGKey(0), x)
    out = gn.apply(params, x)

    # composed reference via per-group normalize
    xf = np.asarray(x).reshape(2, -1, 8, 4)
    mean = xf.mean(axis=(1, 3), keepdims=True)
    var = xf.var(axis=(1, 3), keepdims=True)
    ref = ((xf - mean) / np.sqrt(var + 1e-5)).reshape(2, 4, 4, 32)
    ref = ref / (1 + np.exp(-ref))  # silu with weight=1, bias=0
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_group_norm_validation():
    with pytest.raises(ValueError):
        group_norm_nhwc(jnp.ones((1, 2, 2, 30)), 8)
    with pytest.raises(ValueError):
        group_norm_nhwc(jnp.ones((1, 2, 2, 32)), 8, act="tanh")


# ------------------------------------------------------------- groupbn

def test_batch_norm_nhwc_train_eval_and_fused_add_relu():
    x = jnp.asarray(np.random.RandomState(0).randn(4, 3, 3, 16)
                    .astype("float32"))
    z = jnp.asarray(np.random.RandomState(1).randn(4, 3, 3, 16)
                    .astype("float32"))
    bn = BatchNorm2d_NHWC(16, fuse_relu=True)
    variables = bn.init(jax.random.PRNGKey(0), x)
    out, mutated = bn.apply(variables, x, z=z, train=True,
                            mutable=["batch_stats"])
    xf = np.asarray(x)
    mean = xf.mean(axis=(0, 1, 2))
    var = xf.var(axis=(0, 1, 2))
    ref = np.maximum((xf - mean) / np.sqrt(var + 1e-5) + np.asarray(z), 0.0)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-4)
    # torch-convention momentum (0.1 weight on the new batch stats)
    rm = np.asarray(mutated["batch_stats"]["running_mean"])
    np.testing.assert_allclose(rm, 0.1 * mean, rtol=1e-4, atol=1e-5)

    # eval path uses running stats
    out_eval = bn.apply(
        {"params": variables["params"], "batch_stats": mutated["batch_stats"]},
        x, train=False)
    assert np.isfinite(np.asarray(out_eval)).all()


def test_batch_norm_nhwc_group_sync():
    """bn_group>1: stats combine across the mesh axis exactly like
    computing them on the concatenated batch."""
    x = jnp.asarray(np.random.RandomState(0).randn(8, 2, 2, 4)
                    .astype("float32"))
    bn = BatchNorm2d_NHWC(4, bn_group=8, axis_name="data", momentum=1.0)
    mesh = jax.make_mesh((8,), ("data",))
    # init outside shard_map: train=False avoids the group pmean
    variables = bn.init(jax.random.PRNGKey(0), x[:1], train=False)

    def f(x_local):
        out, mut = bn.apply(variables, x_local, train=True,
                            mutable=["batch_stats"])
        return out, mut["batch_stats"]["running_mean"]

    out, means = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=P("data"), out_specs=(P("data"), P("data"))))(x)
    # every shard saw the same (global) mean -> momentum 0 writes it
    global_mean = np.asarray(x).mean(axis=(0, 1, 2))
    np.testing.assert_allclose(np.asarray(means).reshape(8, 4)[0],
                               global_mean, rtol=1e-5, atol=1e-6)


def test_batch_norm_nhwc_subgroup_sync():
    """bn_group smaller than the axis: stats combine only within each
    contiguous group of bn_group devices."""
    x = jnp.asarray(np.random.RandomState(0).randn(8, 2, 2, 4)
                    .astype("float32"))
    bn = BatchNorm2d_NHWC(4, bn_group=4, axis_name="data", momentum=1.0)
    mesh = jax.make_mesh((8,), ("data",))
    variables = bn.init(jax.random.PRNGKey(0), x[:1], train=False)

    def f(x_local):
        _, mut = bn.apply(variables, x_local, train=True,
                          mutable=["batch_stats"])
        return mut["batch_stats"]["running_mean"][None]

    means = np.asarray(jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=P("data"), out_specs=P("data")))(x))
    first_half = np.asarray(x)[:4].mean(axis=(0, 1, 2))
    second_half = np.asarray(x)[4:].mean(axis=(0, 1, 2))
    np.testing.assert_allclose(means[0], first_half, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(means[7], second_half, rtol=1e-5, atol=1e-6)
    assert not np.allclose(means[0], means[7])


# ---------------------------------------------------------- focal_loss

def test_focal_loss_reduces_easy_examples():
    logits = jnp.asarray([[5.0, -5.0], [0.1, -0.1]])
    targets = jnp.asarray([0, 0])
    per = focal_loss(logits, targets, reduction="none")
    # confident correct example has much smaller loss than uncertain one
    assert float(per[0].sum()) < float(per[1].sum()) * 0.1


def test_focal_loss_gamma_zero_is_weighted_bce():
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(6, 4).astype("float32"))
    targets = jnp.asarray(rng.randint(0, 4, 6))
    got = focal_loss(logits, targets, alpha=0.5, gamma=0.0)
    x = np.asarray(logits)
    t = np.eye(4)[np.asarray(targets)]
    bce = np.maximum(x, 0) - x * t + np.log1p(np.exp(-np.abs(x)))
    np.testing.assert_allclose(float(got), 0.5 * bce.sum(), rtol=1e-5)


def test_focal_loss_ignore_negative_targets():
    logits = jnp.zeros((2, 3))
    l_all = focal_loss(logits, jnp.asarray([-1, -1]))
    # background-only: positive term absent but negative-class term remains
    assert float(l_all) > 0


# ------------------------------------------------------- index_mul_2d

def test_index_mul_2d_fwd_bwd():
    in1 = jnp.asarray(np.random.RandomState(0).randn(5, 3).astype("f4"))
    in2 = jnp.asarray(np.random.RandomState(1).randn(4, 3).astype("f4"))
    idx = jnp.asarray([0, 2, 2, 4])
    out = index_mul_2d(in1, in2, idx)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(in1)[np.asarray(idx)] *
                               np.asarray(in2), rtol=1e-6)
    g1 = jax.grad(lambda a: jnp.sum(index_mul_2d(a, in2, idx)))(in1)
    # row 2 referenced twice -> grads accumulate
    np.testing.assert_allclose(np.asarray(g1)[2],
                               np.asarray(in2)[1] + np.asarray(in2)[2],
                               rtol=1e-6)


# ------------------------------------------------------------ sparsity

def test_m4n2_mask_keeps_two_of_four():
    w = jnp.asarray(np.random.RandomState(0).randn(16, 8).astype("f4"))
    mask = m4n2_1d_mask(w)
    groups = np.asarray(mask).reshape(4, 4, 8)
    np.testing.assert_array_equal(groups.sum(axis=1), 2)
    # the kept entries are the two largest |w| per group
    wabs = np.abs(np.asarray(w)).reshape(4, 4, 8)
    for g in range(4):
        for c in range(8):
            kept = wabs[g, :, c][groups[g, :, c]]
            dropped = wabs[g, :, c][~groups[g, :, c]]
            assert kept.min() >= dropped.max() - 1e-7


def test_compute_and_apply_masks_eligibility():
    params = {
        "dense": {"kernel": jnp.ones((8, 4)), "bias": jnp.ones((4,))},
        "embedding": {"table": jnp.ones((8, 4))},
        "odd": jnp.ones((3, 4)),  # not divisible by 4 -> dense
    }
    masks = compute_sparse_masks(params)
    masked = apply_masks(params, masks)
    assert sparsity_ratio(params, masks) == 0.5
    np.testing.assert_array_equal(np.asarray(masked["dense"]["bias"]), 1.0)
    np.testing.assert_array_equal(np.asarray(masked["embedding"]["table"]),
                                  1.0)
    np.testing.assert_array_equal(np.asarray(masked["odd"]), 1.0)
    assert float(jnp.mean(masked["dense"]["kernel"])) == 0.5


def test_masked_optimizer_keeps_slots_pruned():
    from apex_tpu.optimizers import FusedAdam

    params = {"w": jnp.asarray(np.random.RandomState(0).randn(8, 4)
                               .astype("f4"))}
    ASP.restore_pruned_weights()
    masked_params, masks = ASP.init_model_for_pruning(
        params, disallowed_layer_names=("nothing",))
    opt = ASP.init_optimizer_for_pruning(FusedAdam(lr=0.1))
    state = opt.init(masked_params)
    p = masked_params
    for i in range(3):
        grads = {"w": jnp.ones_like(p["w"])}
        p, state = opt.step(grads, state, p)
    w = np.asarray(p["w"])
    keep = np.asarray(masks["w"])
    assert (w[~keep] == 0).all()          # pruned slots stay zero
    assert (np.abs(w[keep]) > 0).all()    # live slots trained
    assert ASP.is_sparsity_enabled()
    ASP.restore_pruned_weights()
    assert not ASP.is_sparsity_enabled()


# ---------------------------------------------------------- bottleneck

@pytest.mark.slow
def test_bottleneck_shapes_and_residual():
    x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 8, 16)
                    .astype("float32"))
    blk = Bottleneck(16, 8, 16)
    variables = blk.init(jax.random.PRNGKey(0), x)
    out, _ = blk.apply(variables, x, train=True, mutable=["batch_stats"])
    assert out.shape == (2, 8, 8, 16)
    assert (np.asarray(out) >= 0).all()  # final fused relu

    blk2 = Bottleneck(16, 8, 32, stride=2)
    v2 = blk2.init(jax.random.PRNGKey(0), x)
    out2, _ = blk2.apply(v2, x, train=True, mutable=["batch_stats"])
    assert out2.shape == (2, 4, 4, 32)


def test_halo_exchange_matches_full_conv():
    """Spatially-sharded 3x3 conv with halo exchange == full-image conv."""
    N, H, W, C = 2, 16, 8, 4
    x = jnp.asarray(np.random.RandomState(0).randn(N, H, W, C)
                    .astype("float32"))
    kernel = jnp.asarray(np.random.RandomState(1).randn(3, 3, C, C)
                         .astype("float32") * 0.2)
    mesh = jax.make_mesh((8,), ("spatial",))

    def sharded(x_local):
        padded = HaloExchanger1d("spatial", 1)(x_local)
        return jax.lax.conv_general_dilated(
            padded, kernel, (1, 1), ((0, 0), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    out = jax.jit(jax.shard_map(
        sharded, mesh=mesh, in_specs=P(None, "spatial"),
        out_specs=P(None, "spatial")))(x)

    ref = jax.lax.conv_general_dilated(
        x, kernel, (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_spatial_bottleneck_runs_sharded():
    x = jnp.asarray(np.random.RandomState(0).randn(1, 16, 4, 8)
                    .astype("float32"))
    blk = SpatialBottleneck(8, 4, 8, spatial_axis="spatial")
    mesh = jax.make_mesh((8,), ("spatial",))

    def init_and_apply(x_local):
        variables = blk.init(jax.random.PRNGKey(0), x_local, False)
        out, _ = blk.apply(variables, x_local, train=True,
                           mutable=["batch_stats"])
        return out

    out = jax.jit(jax.shard_map(
        init_and_apply, mesh=mesh, in_specs=P(None, "spatial"),
        out_specs=P(None, "spatial")))(x)
    assert out.shape == x.shape
    assert np.isfinite(np.asarray(out)).all()


def test_spatial_bottleneck_peer_group_size_threads_to_exchanger():
    """peer_group_size reaches the bottleneck's own halo exchange (the
    reference wires PeerMemoryPool's peer_group_size through
    SpatialBottleneck): group borders behave as image borders, so the
    output of two 4-rank groups matches two independent 4-rank runs."""
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(1, 16, 4, 8).astype("float32"))
    mesh8 = jax.make_mesh((8,), ("spatial",))
    mesh4 = jax.make_mesh((4,), ("spatial",),
                          devices=jax.devices()[:4])
    blk_g = SpatialBottleneck(8, 4, 8, spatial_axis="spatial",
                              peer_group_size=4)
    blk_1 = SpatialBottleneck(8, 4, 8, spatial_axis="spatial")
    mesh1 = jax.make_mesh((1,), ("spatial",), devices=jax.devices()[:1])
    variables = jax.jit(jax.shard_map(
        lambda xl: blk_1.init(jax.random.PRNGKey(0), xl, False),
        mesh=mesh1, in_specs=P(None, "spatial"), out_specs=P()))(x[:, :2])
    variables = jax.tree.map(np.asarray, variables)

    def apply(blk):
        def f(variables, x_local):
            out, _ = blk.apply(variables, x_local, train=False,
                               mutable=["batch_stats"])
            return out
        return f

    grouped = jax.jit(jax.shard_map(
        apply(blk_g), mesh=mesh8, in_specs=(P(), P(None, "spatial")),
        out_specs=P(None, "spatial")))(variables, x)
    halves = [
        jax.jit(jax.shard_map(
            apply(blk_1), mesh=mesh4, in_specs=(P(), P(None, "spatial")),
            out_specs=P(None, "spatial")))(variables, half)
        for half in (x[:, :8], x[:, 8:])
    ]
    np.testing.assert_allclose(np.asarray(grouped),
                               np.concatenate([np.asarray(h) for h in halves],
                                              axis=1),
                               rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------- transducer

def _np_rnnt_loss(log_probs, labels, T, U):
    """Numpy alpha-recursion reference (single example)."""
    lp = np.asarray(log_probs, np.float64)
    alpha = np.full((T, U + 1), -np.inf)
    alpha[0, 0] = 0.0
    for t in range(T):
        for u in range(U + 1):
            cands = []
            if t > 0:
                cands.append(alpha[t - 1, u] + lp[t - 1, u, 0])  # blank
            if u > 0:
                cands.append(alpha[t, u - 1] + lp[t, u - 1, labels[u - 1]])
            if cands:
                alpha[t, u] = np.logaddexp.reduce(cands)
    return -(alpha[T - 1, U] + lp[T - 1, U, 0])


def test_transducer_loss_matches_numpy_dp():
    from apex_tpu.contrib.transducer import transducer_loss

    rng = np.random.RandomState(0)
    B, T, U, V = 3, 6, 4, 8
    logits = rng.randn(B, T, U + 1, V).astype("f4")
    log_probs = jnp.asarray(logits) - jax.nn.logsumexp(
        jnp.asarray(logits), axis=-1, keepdims=True)
    labels = jnp.asarray(rng.randint(1, V, (B, U)))
    f_len = jnp.asarray([T, T - 1, T - 2])
    y_len = jnp.asarray([U, U - 1, U - 2])

    loss = transducer_loss(log_probs, labels, f_len, y_len)
    for b in range(B):
        ref = _np_rnnt_loss(np.asarray(log_probs[b]), np.asarray(labels[b]),
                            int(f_len[b]), int(y_len[b]))
        np.testing.assert_allclose(float(loss[b]), ref, rtol=1e-4)


def test_transducer_loss_grad_is_finite_and_nonzero():
    from apex_tpu.contrib.transducer import transducer_loss

    rng = np.random.RandomState(1)
    B, T, U, V = 2, 5, 3, 6
    logits = jnp.asarray(rng.randn(B, T, U + 1, V).astype("f4"))
    labels = jnp.asarray(rng.randint(1, V, (B, U)))
    f_len = jnp.full((B,), T)
    y_len = jnp.full((B,), U)

    def loss_fn(lg):
        lp = lg - jax.nn.logsumexp(lg, axis=-1, keepdims=True)
        return jnp.sum(transducer_loss(lp, labels, f_len, y_len))

    g = jax.jit(jax.grad(loss_fn))(logits)
    assert np.isfinite(np.asarray(g)).all()
    assert float(jnp.max(jnp.abs(g))) > 0


def test_transducer_joint_broadcast_and_relu():
    from apex_tpu.contrib.transducer import transducer_joint

    f = jnp.asarray(np.random.RandomState(0).randn(2, 4, 8).astype("f4"))
    g = jnp.asarray(np.random.RandomState(1).randn(2, 3, 8).astype("f4"))
    out = transducer_joint(f, g)
    assert out.shape == (2, 4, 3, 8)
    np.testing.assert_allclose(
        np.asarray(out[0, 1, 2]), np.asarray(f[0, 1]) + np.asarray(g[0, 2]),
        rtol=1e-6)
    out_relu = transducer_joint(f, g, relu=True)
    assert float(jnp.min(out_relu)) >= 0.0


# ------------------------------------------------- conv_bias_relu / gbn

def test_conv_bias_relu_matches_composed():
    from apex_tpu.contrib.conv_bias_relu import (
        conv_bias,
        conv_bias_mask_relu,
        conv_bias_relu,
        conv_frozen_scale_bias_relu,
    )

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 8, 8, 4).astype("f4"))
    w = jnp.asarray(rng.randn(3, 3, 4, 6).astype("f4") * 0.2)
    b = jnp.asarray(rng.randn(6).astype("f4"))

    ref = jax.lax.conv_general_dilated(
        x, w, (1, 1), ((1, 1), (1, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC")) + b
    np.testing.assert_allclose(np.asarray(conv_bias(x, w, b, padding=1)),
                               np.asarray(ref), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(conv_bias_relu(x, w, b, padding=1)),
        np.maximum(np.asarray(ref), 0), rtol=1e-4, atol=1e-5)

    mask = jnp.asarray(rng.rand(2, 8, 8, 6) < 0.5).astype("f4")
    np.testing.assert_allclose(
        np.asarray(conv_bias_mask_relu(x, w, b, mask, padding=1)),
        np.maximum(np.asarray(ref) * np.asarray(mask), 0),
        rtol=1e-4, atol=1e-5)

    scale = jnp.asarray(rng.rand(6).astype("f4") + 0.5)
    ref_fs = jax.lax.conv_general_dilated(
        x, w, (1, 1), ((1, 1), (1, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC")) * scale + b
    np.testing.assert_allclose(
        np.asarray(conv_frozen_scale_bias_relu(x, w, scale, b, padding=1)),
        np.maximum(np.asarray(ref_fs), 0), rtol=1e-4, atol=1e-5)


def test_cudnn_gbn_alias():
    from apex_tpu.contrib.cudnn_gbn import GroupBatchNorm2d

    # reference positional signature: (num_features, group_size)
    bn = GroupBatchNorm2d(8, 2, axis_name="data")
    assert bn.bn_group == 2 and bn.eps == 1e-5
    x = jnp.ones((2, 3, 3, 8))
    variables = bn.init(jax.random.PRNGKey(0), x, train=False)
    out = bn.apply(variables, x, train=False)
    assert out.shape == x.shape


def test_peer_memory_halo_and_send_recv():
    """contrib.peer_memory surface (reference: apex/contrib/peer_memory/
    (U)): the pool-shaped exchanger equals HaloExchanger1d, and
    peer_send_recv performs one ring hop."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.contrib.peer_memory import (
        PeerHaloExchanger1d,
        PeerMemoryPool,
        peer_send_recv,
    )

    mesh = jax.make_mesh((8,), ("spatial",))
    x = jnp.arange(8 * 4, dtype=jnp.float32).reshape(8, 4)  # 8 shards

    def hop(x_local):
        return peer_send_recv(x_local, "spatial", shift=1)

    out = jax.jit(jax.shard_map(hop, mesh=mesh, in_specs=P("spatial"),
                                out_specs=P("spatial")))(x)
    # shard i receives shard i-1's rows (ring)
    np.testing.assert_array_equal(np.asarray(out), np.roll(x, 1, axis=0))

    pool = PeerMemoryPool(axis_name="spatial")
    ex = PeerHaloExchanger1d(pool, half_halo=1)
    img = jnp.arange(8 * 2 * 3 * 1, dtype=jnp.float32).reshape(1, 16, 3, 1)

    def halo(img_local):
        return ex(img_local)

    padded = jax.jit(jax.shard_map(
        halo, mesh=mesh, in_specs=P(None, "spatial"),
        out_specs=P(None, "spatial")))(img)
    # each 2-row shard gains one halo row per side -> 4 rows per shard
    assert padded.shape == (1, 32, 3, 1)
    full = np.asarray(img)[0, :, :, 0]
    got = np.asarray(padded)[0].reshape(8, 4, 3)[3]  # shard 3
    np.testing.assert_array_equal(got[0], full[2 * 3 - 1])  # prev edge
    np.testing.assert_array_equal(got[1:3], full[6:8])      # own rows
    np.testing.assert_array_equal(got[3], full[8])          # next edge


def test_peer_memory_group_size_isolates_groups():
    """peer_group_size=4 on an 8-rank axis: halos never cross the group
    border (rank 3's next-halo and rank 4's prev-halo are zero), and the
    reference 4-arg constructor form ports."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.contrib.peer_memory import (
        PeerHaloExchanger1d,
        PeerMemoryPool,
    )

    mesh = jax.make_mesh((8,), ("spatial",))
    pool = PeerMemoryPool(axis_name="spatial", peer_group_size=4)
    # reference ctor shape: (ranks, rank_in_group, pool, half_halo)
    ex = PeerHaloExchanger1d(list(range(8)), 0, pool, 1)
    img = jnp.arange(16.0).reshape(1, 16, 1, 1) + 1.0  # rows 1..16

    padded = jax.jit(jax.shard_map(
        lambda t: ex(t), mesh=mesh, in_specs=P(None, "spatial"),
        out_specs=P(None, "spatial")))(img)
    shards = np.asarray(padded)[0].reshape(8, 4)  # 2 own rows + 2 halos
    # group border between rank 3 and 4: no leakage either way
    assert shards[3, 3] == 0.0   # rank 3 next-halo zeroed (group edge)
    assert shards[4, 0] == 0.0   # rank 4 prev-halo zeroed (group edge)
    # interior neighbor still exchanged
    assert shards[1, 0] == 2.0   # rank 1 prev-halo = rank 0's last row
    assert shards[2, 3] == 7.0   # rank 2 next-halo = rank 3's first row


def test_peer_memory_rejects_non_dividing_group_size():
    """group_size that does not divide the axis would wrap the last
    rank's halo around the ring (cross-image leakage) — must raise."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu.contrib.bottleneck import HaloExchanger1d

    mesh = jax.make_mesh((8,), ("spatial",))
    ex = HaloExchanger1d("spatial", 1, group_size=3)
    img = jnp.zeros((1, 16, 1, 1))
    with pytest.raises(ValueError, match="must divide"):
        jax.jit(jax.shard_map(
            lambda t: ex(t), mesh=mesh, in_specs=P(None, "spatial"),
            out_specs=P(None, "spatial")))(img)


def test_transducer_packed_matches_dense():
    """Packed-mode parity (reference packed_input/pack_output): joint
    pack_output -> packed loss == dense loss, per example."""
    from apex_tpu.contrib.transducer import (
        TransducerJoint,
        TransducerLoss,
        transducer_batch_offset,
    )

    rng = np.random.RandomState(0)
    B, T, U, V = 3, 7, 4, 6
    f = jnp.asarray(rng.randn(B, T, V).astype("float32"))
    g = jnp.asarray(rng.randn(B, U + 1, V).astype("float32"))
    labels = jnp.asarray(rng.randint(1, V, (B, U)))
    f_len = jnp.asarray([7, 5, 3], jnp.int32)
    y_len = jnp.asarray([4, 2, 3], jnp.int32)
    g_len = y_len + 1

    dense_joint = TransducerJoint()(f, g)
    log_probs = jax.nn.log_softmax(dense_joint, axis=-1)
    dense_loss = TransducerLoss()(log_probs, labels, f_len, y_len)

    offs = transducer_batch_offset(f_len, y_len)
    packed_size = int(B * T * (U + 1))  # static capacity with slack
    packed = TransducerJoint(pack_output=True)(
        f, g, f_len, g_len, batch_offset=offs, packed_size=packed_size)
    packed_lp = jax.nn.log_softmax(packed, axis=-1)
    packed_loss = TransducerLoss(packed_input=True)(
        packed_lp, labels, f_len, y_len, batch_offset=offs, max_f_len=T)

    np.testing.assert_allclose(np.asarray(packed_loss),
                               np.asarray(dense_loss), rtol=1e-5, atol=1e-5)


def test_transducer_pack_unpack_roundtrip():
    from apex_tpu.contrib.transducer import (
        transducer_batch_offset,
        transducer_pack,
        transducer_unpack,
    )

    rng = np.random.RandomState(1)
    B, T, U1, H = 2, 5, 3, 4
    dense = jnp.asarray(rng.randn(B, T, U1, H).astype("float32"))
    f_len = jnp.asarray([5, 2], jnp.int32)
    y_len = jnp.asarray([2, 1], jnp.int32)
    offs = transducer_batch_offset(f_len, y_len)
    packed = transducer_pack(dense, f_len, y_len, B * T * U1, offs)
    back = transducer_unpack(packed, f_len, y_len, T, U1, offs, fill=0.0)
    # valid cells round-trip exactly; padding cells come back as fill
    for b in range(B):
        fl, w = int(f_len[b]), int(y_len[b]) + 1
        np.testing.assert_array_equal(np.asarray(back)[b, :fl, :w],
                                      np.asarray(dense)[b, :fl, :w])
    assert float(jnp.abs(back[1, 2:, :]).max()) == 0.0


def test_transducer_pack_zero_size_examples():
    """Zero-size examples (f_len == 0) create duplicate batch offsets;
    the searchsorted coordinate map must resolve positions at the
    duplicate run to the non-empty successor, not the empty example
    (round-3 advisor finding — verified safe, locked in here)."""
    from apex_tpu.contrib.transducer import (
        transducer_batch_offset,
        transducer_pack,
        transducer_unpack,
    )

    rng = np.random.RandomState(2)
    B, T, U1, H = 4, 3, 3, 2
    dense = jnp.asarray(rng.randn(B, T, U1, H).astype("float32"))
    # examples 1 and 3 are empty (f_len 0); 3 is also terminal
    f_len = jnp.asarray([3, 0, 2, 0], jnp.int32)
    y_len = jnp.asarray([2, 1, 0, 2], jnp.int32)
    offs = transducer_batch_offset(f_len, y_len)
    assert list(np.asarray(offs)) == [0, 9, 9, 11]  # duplicate at 9
    packed = transducer_pack(dense, f_len, y_len, B * T * U1, offs)
    # example 2's block starts AT the duplicate offset and must hold
    # example 2's cells, not example 1's (which has none)
    np.testing.assert_array_equal(
        np.asarray(packed)[9:11],
        np.asarray(dense)[2, :2, :1].reshape(2, H))
    back = transducer_unpack(packed, f_len, y_len, T, U1, offs, fill=0.0)
    for b in range(B):
        fl, w = int(f_len[b]), int(y_len[b]) + 1
        np.testing.assert_array_equal(np.asarray(back)[b, :fl, :w],
                                      np.asarray(dense)[b, :fl, :w])
    # empty examples come back all-fill
    assert float(jnp.abs(back[1]).max()) == 0.0
    assert float(jnp.abs(back[3]).max()) == 0.0


# -------------------------------------------------- permutation search

def test_permutation_search_improves_retained_magnitude():
    """A weight built so identity grouping is pessimal (each group of 4
    holds one large 'family'): the search must regroup and retain
    strictly more magnitude; with permutation the mask stays exactly
    2:4 in the searched grouping."""
    from apex_tpu.contrib.sparsity import (
        compute_sparse_masks,
        magnitude_efficacy,
        m4n2_1d_mask,
        search_for_good_permutation,
    )

    rng = np.random.RandomState(0)
    R, C = 32, 16
    # adversarial: rows 4k..4k+3 all large in the same columns, so
    # identity groups must drop half the large values; interleaving
    # groups keeps all of them
    w = np.full((R, C), 0.01, np.float32)
    for g in range(R // 4):
        w[4 * g:4 * g + 4, :] += rng.rand(1, C) * (1 + g)
    w = jnp.asarray(w * (1 + 0.001 * rng.rand(R, C)))

    base = magnitude_efficacy(np.asarray(w))
    perm = search_for_good_permutation(w)
    tuned = magnitude_efficacy(np.asarray(w), perm)
    assert tuned > base + 0.01, (base, tuned)
    assert sorted(perm.tolist()) == list(range(R))

    masks = compute_sparse_masks({"linear": w}, allow_permutation=True)
    mask = masks["linear"]
    # exactly 50% kept, and 2-of-4 in the PERMUTED grouping
    assert float(jnp.mean(mask.astype(jnp.float32))) == 0.5
    grouped = np.asarray(mask)[perm].reshape(-1, 4, C).sum(axis=1)
    np.testing.assert_array_equal(grouped, np.full_like(grouped, 2))
    # retained magnitude via the permuted mask > identity-grouping mask
    ident = np.abs(np.asarray(w))[np.asarray(m4n2_1d_mask(w))].sum()
    permed = np.abs(np.asarray(w))[np.asarray(mask)].sum()
    assert permed > ident


def test_permutation_search_deterministic_and_identity_safe():
    from apex_tpu.contrib.sparsity import search_for_good_permutation

    rng = np.random.RandomState(1)
    w = jnp.asarray(rng.randn(16, 8).astype("f4"))
    p1 = search_for_good_permutation(w)
    p2 = search_for_good_permutation(w)
    np.testing.assert_array_equal(p1, p2)
    # a single group: nothing to search
    small = jnp.asarray(rng.randn(4, 8).astype("f4"))
    np.testing.assert_array_equal(search_for_good_permutation(small),
                                  np.arange(4))
