"""contrib.openfold tests (reference: apex/contrib/openfold_triton/ —
the Evoformer kernel tier + FusedAdamSWA; SURVEY.md §2.2 V? row)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.openfold import (
    FusedAdamSWA,
    LayerNormSmallShapeOptImpl,
    gated_attention,
    layer_norm,
    softmax,
)


def _ln_ref(x, w, b, eps=1e-5):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + eps) * w + b).astype(x.dtype)


def test_layer_norm_pair_representation_shape():
    # (B, N, N, c_z) with c_z=128 — the pair-rep LayerNorm shape the
    # Triton tier was built for
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 8, 8, 128).astype("f4"))
    w = jnp.asarray(rng.rand(128).astype("f4") + 0.5)
    b = jnp.asarray(rng.randn(128).astype("f4"))
    np.testing.assert_allclose(np.asarray(layer_norm(x, w, b)),
                               np.asarray(_ln_ref(x, w, b)),
                               atol=2e-5, rtol=2e-5)
    # grads flow to all three
    g = jax.grad(lambda x, w, b: jnp.sum(layer_norm(x, w, b) ** 2),
                 argnums=(0, 1, 2))(x, w, b)
    assert all(np.isfinite(np.asarray(t)).all() for t in g)


def test_layer_norm_small_shape_impl_apply():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(4, 6, 64).astype("f4"))
    w = jnp.ones((64,), jnp.float32)
    b = jnp.zeros((64,), jnp.float32)
    y = LayerNormSmallShapeOptImpl.apply(x, (64,), w, b)
    np.testing.assert_allclose(np.asarray(y), np.asarray(_ln_ref(x, w, b)),
                               atol=2e-5)

    # multi-dim normalized_shape: normalize over the flattened trailing
    # dims (the Triton entry's semantics)
    x2 = jnp.asarray(np.random.RandomState(5).randn(4, 6, 8).astype("f4"))
    w2 = jnp.ones((6, 8), jnp.float32)
    b2 = jnp.zeros((6, 8), jnp.float32)
    y2 = LayerNormSmallShapeOptImpl.apply(x2, (6, 8), w2, b2)
    want = _ln_ref(x2.reshape(4, 48), w2.reshape(48),
                   b2.reshape(48)).reshape(4, 6, 8)
    np.testing.assert_allclose(np.asarray(y2), np.asarray(want), atol=2e-5)


def test_layer_norm_small_shape_impl_rejects_mismatched_shape():
    """A normalized_shape that merely DIVIDES x.size must raise, not
    silently normalize the wrong element grouping (advisor r5 #3):
    here (8,) divides 4*6*64 but the trailing dim is 64."""
    x = jnp.ones((4, 6, 64), jnp.float32)
    w = jnp.ones((8,), jnp.float32)
    b = jnp.zeros((8,), jnp.float32)
    with pytest.raises(ValueError, match="normalized_shape"):
        LayerNormSmallShapeOptImpl.apply(x, (8,), w, b)


def test_softmax_bias_mask_matches_composition():
    """softmax(scale*x + pair_bias) with a padding mask must equal the
    jnp composition — the Evoformer score softmax contract."""
    rng = np.random.RandomState(2)
    B, s, H, N = 2, 3, 4, 16
    x = jnp.asarray(rng.randn(B, s, H, N, N).astype("f4"))
    bias = jnp.asarray(rng.randn(B, 1, H, N, N).astype("f4"))
    mask = jnp.asarray(rng.rand(B, 1, 1, 1, N) > 0.8)

    got = softmax(x, mask=mask, bias=bias, scale=0.25)
    xf = x * 0.25 + bias
    xf = jnp.where(mask, -1e9, xf)
    want = jax.nn.softmax(xf, axis=-1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)
    # masked probabilities are exactly renormalized away
    assert float(jnp.max(jnp.where(mask, got, 0.0))) < 1e-6


def test_gated_attention_matches_manual():
    rng = np.random.RandomState(3)
    B, H, S, D = 2, 4, 8, 16
    q, k, v, gate = (jnp.asarray(rng.randn(B, H, S, D).astype("f4"))
                     for _ in range(4))
    bias = jnp.asarray(rng.randn(B, H, S, S).astype("f4") * 0.1)
    scale = 1.0 / np.sqrt(D)

    got = gated_attention(q, k, v, gate, bias=bias, scale=scale)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale + bias
    want = jax.nn.sigmoid(gate) * jnp.einsum(
        "bhqk,bhkd->bhqd", jax.nn.softmax(scores, -1), v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=3e-5, rtol=3e-5)


def test_fused_adam_swa_matches_fused_adam_plus_average():
    """The fused step must equal FusedAdam's update followed by the SWA
    EMA — fusion is an implementation economy, not new math."""
    from apex_tpu.optimizers import FusedAdam

    rng = np.random.RandomState(4)
    params = {"w": jnp.asarray(rng.randn(8, 8).astype("f4")),
              "b": jnp.asarray(rng.randn(8).astype("f4"))}
    grads = jax.tree.map(lambda p: p * 0.1, params)

    d = 0.75
    swa_opt = FusedAdamSWA(lr=1e-2, weight_decay=0.01, swa_decay_rate=d)
    ref_opt = FusedAdam(lr=1e-2, weight_decay=0.01)
    st = swa_opt.init(params)
    rst = ref_opt.init(params)
    # fresh state: the average starts at the initial params
    jax.tree.map(lambda s, p: np.testing.assert_array_equal(
        np.asarray(s), np.asarray(p)), st.swa, params)

    p1, st1 = swa_opt.step(grads, st, params)
    rp1, rst1 = ref_opt.step(grads, rst, params)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-6), p1, rp1)
    # first step: the average starts AT the first updated params (the
    # AveragedModel first-capture contract) — no blend with the init
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-6), st1.swa, p1)

    # second step onward: the EMA blend
    p2, st2 = swa_opt.step(grads, st1, p1)
    want_swa = jax.tree.map(
        lambda s, p: d * s + (1 - d) * p.astype(jnp.float32),
        st1.swa, p2)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-6), st2.swa, want_swa)

    # swa_params casts to the model dtypes
    out = swa_opt.swa_params(st1, like=params)
    assert jax.tree.leaves(out)[0].dtype == jnp.float32


def test_fused_adam_swa_skip_and_masters():
    params = {"w": jnp.ones((4, 4), jnp.bfloat16)}
    grads = {"w": jnp.full((4, 4), 0.1, jnp.bfloat16)}
    opt = FusedAdamSWA(lr=1e-2, master_weights=True)
    st = opt.init(params)
    assert jax.tree.leaves(st.master)[0].dtype == jnp.float32

    # overflow skip: nothing moves, counter does not advance
    p2, st2 = opt.step(grads, st, params, skip_if=jnp.asarray(True))
    np.testing.assert_array_equal(np.asarray(p2["w"], np.float32),
                                  np.asarray(params["w"], np.float32))
    assert int(st2.step) == 0
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), st2.swa, st.swa)

    # real step: swa tracks the fp32 MASTER trajectory, not the bf16
    # cast — and the FIRST step copies the master (no blend)
    p3, st3 = opt.step(grads, st, params, skip_if=jnp.asarray(False))
    assert int(st3.step) == 1
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-6), st3.swa, st3.master)
    assert p3["w"].dtype == jnp.bfloat16

    # second real step: the EMA blend over the master trajectory
    p4, st4 = opt.step(grads, st3, p3, skip_if=jnp.asarray(False))
    assert int(st4.step) == 2
    want = jax.tree.map(
        lambda s, m: 0.9 * s + 0.1 * m, st3.swa, st4.master)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-6), st4.swa, want)


def test_fused_adam_swa_under_jit():
    params = {"w": jnp.ones((8,), jnp.float32)}
    grads = {"w": jnp.full((8,), 0.2)}
    opt = FusedAdamSWA(lr=1e-3)
    st = opt.init(params)

    @jax.jit
    def step(p, s):
        return opt.step(grads, s, p)

    p, s = step(params, st)
    p, s = step(p, s)
    assert int(s.step) == 2
    assert np.isfinite(np.asarray(s.swa["w"])).all()
