"""Pipeline schedule efficiency: the tick/bubble contract, analytic and
measured (VERDICT r4 weak #5 — efficiency was asserted, never measured).

Mirrors the upstream 1F1B contract (warmup ``pp-1`` + steady ``m`` ticks,
bubble ``(pp-1)/(m+pp-1)``) and the interleaved variant's
``v*m + pp - 1`` ticks at ``1/v`` per-tick work."""

import jax
import numpy as np
import pytest

from apex_tpu.transformer.pipeline_parallel.efficiency import (
    measure_pipeline_ticks,
    tick_accounting,
)


def test_tick_accounting_1f1b_contract():
    # the VERDICT-named assertion: total ticks == m + pp - 1
    for pp, m in [(2, 2), (4, 8), (8, 32)]:
        acc = tick_accounting(pp, m)
        assert acc["total_ticks"] == m + pp - 1
        assert acc["active_ticks_per_stage"] == m
        np.testing.assert_allclose(acc["utilization"], m / (m + pp - 1))
        np.testing.assert_allclose(acc["bubble_fraction"],
                                   (pp - 1) / (m + pp - 1))
    # more microbatches amortize the bubble monotonically
    bubbles = [tick_accounting(4, m)["bubble_fraction"]
               for m in (2, 4, 8, 16, 64)]
    assert bubbles == sorted(bubbles, reverse=True)


def test_tick_accounting_interleaving_shrinks_bubble_time():
    """Interleaving (v chunks/device) adds ticks but each costs 1/v of a
    stage: at equal total work the normalized time strictly drops, and
    the bubble's share approaches (pp-1)/(v*m) of a stage."""
    pp, m = 4, 4
    base = tick_accounting(pp, m, num_chunks=1)
    inter = tick_accounting(pp, m, num_chunks=2)
    assert inter["total_ticks"] == 2 * m + pp - 1
    assert inter["time_units"] < base["time_units"]
    # megatron-paper ratio: (m + (pp-1)/v) vs (m + pp - 1)
    np.testing.assert_allclose(inter["time_units"], m + (pp - 1) / 2)
    np.testing.assert_allclose(base["time_units"], m + pp - 1)


def test_tick_accounting_validates():
    with pytest.raises(ValueError):
        tick_accounting(0, 4)
    with pytest.raises(ValueError):
        tick_accounting(4, 4, num_chunks=0)


def test_compiled_tick_count_matches_contract():
    """The MEASURED (from compiled HLO) tick count of both schedules —
    deterministic where wall-clock on a time-shared CI host is not.
    The scan's tick array length in the lowered while-loop IS the trip
    count: m + pp - 1 (1F1B role) and v*m + pp - 1 (interleaved)."""
    from apex_tpu.transformer.pipeline_parallel.efficiency import (
        compiled_tick_count,
    )

    assert jax.device_count() >= 4
    assert compiled_tick_count(4, 8) == 8 + 4 - 1
    assert compiled_tick_count(2, 6) == 6 + 2 - 1
    assert compiled_tick_count(4, 8, num_chunks=2) == 2 * 8 + 4 - 1


@pytest.mark.slow
def test_measured_ticks_wall_clock_sanity():
    """Wall-clock fit on the sim: per-tick slope positive and time
    grows with m. (The structural tick-count claim lives in the HLO
    test above — 1-core CI wall-clock cannot discriminate schedules,
    see the module docstring's slope_over_stage_cost discussion.)"""
    assert jax.device_count() >= 4
    stats = measure_pipeline_ticks(pp=4, microbatch_counts=(2, 8, 16),
                                   hidden=512, mb_size=8, reps=3)
    t = stats["measured"]
    assert t[16] > t[2]
    assert stats["stage_seconds"] > 0
