"""Opt-level property table + initialize() + autocast semantics.

Mirrors upstream ``tests/L0/run_amp/test_basic_casts.py`` /
``test_promotion.py`` coverage (SURVEY.md §4) on the TPU-native surface.
"""

import jax
import jax.numpy as jnp
import pytest

import apex_tpu.amp as amp


def _params():
    return {
        "dense": {"kernel": jnp.ones((4, 4)), "bias": jnp.zeros((4,))},
        "BatchNorm_0": {"scale": jnp.ones((4,)), "bias": jnp.zeros((4,))},
    }


def test_opt_level_properties():
    _, _, h0 = amp.initialize(_params(), None, opt_level="O0", verbosity=0)
    assert h0.properties.loss_scale == 1.0
    assert not h0.properties.patch_torch_functions

    _, _, h1 = amp.initialize(_params(), None, opt_level="O1", verbosity=0)
    assert h1.properties.loss_scale == "dynamic"
    assert h1.properties.patch_torch_functions
    assert h1.properties.cast_model_type is None

    _, _, h2 = amp.initialize(_params(), None, opt_level="O2", verbosity=0)
    assert h2.properties.master_weights
    assert h2.properties.keep_batchnorm_fp32
    assert h2.properties.cast_model_type == jnp.bfloat16

    _, _, h3 = amp.initialize(_params(), None, opt_level="O3", verbosity=0)
    assert h3.properties.loss_scale == 1.0
    assert not h3.properties.master_weights


def test_bad_opt_level_raises():
    with pytest.raises(ValueError):
        amp.initialize(_params(), None, opt_level="O4", verbosity=0)


def test_explicit_override_of_level_defaults():
    _, _, h = amp.initialize(_params(), None, opt_level="O1", loss_scale=512.0, verbosity=0)
    assert h.properties.loss_scale == 512.0
    assert h.scalers[0].loss_scale == 512.0


def test_o2_casts_model_but_keeps_norm_fp32():
    p, _, _ = amp.initialize(_params(), None, opt_level="O2", verbosity=0)
    assert p["dense"]["kernel"].dtype == jnp.bfloat16
    assert p["dense"]["bias"].dtype == jnp.bfloat16
    assert p["BatchNorm_0"]["scale"].dtype == jnp.float32
    assert p["BatchNorm_0"]["bias"].dtype == jnp.float32


def test_o3_casts_everything():
    p, _, _ = amp.initialize(_params(), None, opt_level="O3", verbosity=0)
    assert p["BatchNorm_0"]["scale"].dtype == jnp.bfloat16


def test_o1_leaves_model_fp32():
    p, _, _ = amp.initialize(_params(), None, opt_level="O1", verbosity=0)
    assert p["dense"]["kernel"].dtype == jnp.float32


def test_autocast_whitelist_casts_matmul_to_bf16():
    a = jnp.ones((8, 8), jnp.float32)
    with amp.autocast():
        out = jnp.matmul(a, a)
    assert out.dtype == jnp.bfloat16
    # restored afterwards
    assert jnp.matmul(a, a).dtype == jnp.float32


def test_autocast_blacklist_casts_softmax_to_fp32():
    x = jnp.ones((4, 4), jnp.bfloat16)
    with amp.autocast():
        out = jax.nn.softmax(x)
    assert out.dtype == jnp.float32


def test_autocast_disabled_is_noop():
    a = jnp.ones((8, 8), jnp.float32)
    with amp.autocast(enabled=False):
        assert jnp.matmul(a, a).dtype == jnp.float32


def test_autocast_under_jit_trace():
    """Casts bake into the traced graph (the cast-cache analog: tracing
    dedupes repeated casts via CSE, so this is at least as cheap as the
    reference's cached casts)."""
    a = jnp.ones((8, 8), jnp.float32)

    def f(x):
        with amp.autocast():
            return jnp.matmul(x, x)

    out = jax.jit(f)(a)
    assert out.dtype == jnp.bfloat16


def test_autocast_inner_disabled_wins():
    """torch/apex idiom: autocast(enabled=False) inside an enabled region
    restores full precision for its extent (innermost wins)."""
    a = jnp.ones((4, 4), jnp.float32)
    with amp.autocast():
        with amp.autocast(enabled=False):
            assert jnp.matmul(a, a).dtype == jnp.float32
        assert jnp.matmul(a, a).dtype == jnp.bfloat16
    assert jnp.matmul(a, a).dtype == jnp.float32


def test_autocast_inner_dtype_wins():
    a = jnp.ones((4, 4), jnp.float32)
    with amp.autocast(compute_dtype=jnp.bfloat16):
        with amp.autocast(compute_dtype=jnp.float16):
            assert jnp.matmul(a, a).dtype == jnp.float16
        assert jnp.matmul(a, a).dtype == jnp.bfloat16


def test_autocast_passes_namedtuple_args_through():
    """lax.conv_general_dilated with explicit ConvDimensionNumbers must not
    be mangled by arg casting."""
    x = jnp.ones((1, 8, 8, 3), jnp.float32)
    w = jnp.ones((3, 3, 3, 4), jnp.float32)
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape, ("NHWC", "HWIO", "NHWC"))
    with amp.autocast():
        out = jax.lax.conv_general_dilated(x, w, (1, 1), "SAME", dimension_numbers=dn)
    assert out.shape == (1, 8, 8, 4)
    assert out.dtype == jnp.bfloat16


def test_enabled_false_handle_is_usable():
    """Reference contract: enabled=False runs as if amp were absent, with
    the API surface intact."""
    params = {"w": jnp.ones((4,))}
    p, _, h = amp.initialize(params, None, opt_level="O2", enabled=False, verbosity=0)
    st = h.init_state()
    assert float(st.loss_scale) == 1.0
    (loss, found), grads = h.value_and_grad(lambda q: jnp.sum(q["w"] ** 2), st)(p)
    assert not bool(found)
    st2 = h.update_scale(st, found)
    assert float(st2.loss_scale) == 1.0  # static unity scaler never moves


def test_autocast_nesting_restores_correctly():
    a = jnp.ones((4, 4), jnp.float32)
    with amp.autocast():
        with amp.autocast():
            assert jnp.matmul(a, a).dtype == jnp.bfloat16
        assert jnp.matmul(a, a).dtype == jnp.bfloat16
    assert jnp.matmul(a, a).dtype == jnp.float32


def test_promotion_is_native():
    """apex's promote-to-widest is jax.numpy's native behavior."""
    a = jnp.ones((4,), jnp.bfloat16)
    b = jnp.ones((4,), jnp.float32)
    assert (a + b).dtype == jnp.float32


def test_state_dict_roundtrip():
    """Checkpoint contract (upstream test_checkpointing.py)."""
    _, _, h = amp.initialize(_params(), None, opt_level="O2", verbosity=0)
    h.scaler_states[0] = h.scaler_states[0]._replace(
        loss_scale=jnp.asarray(4096.0, jnp.float32),
        unskipped=jnp.asarray(17, jnp.int32),
    )
    sd = h.state_dict()
    assert sd["loss_scaler0"]["loss_scale"] == 4096.0

    _, _, h2 = amp.initialize(_params(), None, opt_level="O2", verbosity=0)
    h2.load_state_dict(sd)
    assert float(h2.scaler_states[0].loss_scale) == 4096.0
    assert int(h2.scaler_states[0].unskipped) == 17


def test_multiple_losses_get_independent_scalers():
    _, _, h = amp.initialize(_params(), None, opt_level="O2", num_losses=3, verbosity=0)
    assert len(h.scalers) == 3
    st0 = h.init_state(0)
    st0 = h.update_scale(st0, jnp.asarray(True), loss_id=0)
    st1 = h.init_state(1)
    assert float(st0.loss_scale) == 2.0 ** 15
    assert float(st1.loss_scale) == 2.0 ** 16


def test_handle_value_and_grad_end_to_end():
    params = {"w": jnp.ones((4, 4))}
    _, _, h = amp.initialize(params, None, opt_level="O1", verbosity=0)
    st = h.init_state()

    def loss_fn(p):
        y = jnp.matmul(p["w"], p["w"])  # whitelisted: runs bf16 under O1
        return jnp.sum(y.astype(jnp.float32))

    (loss, found), grads = h.value_and_grad(loss_fn, st)(params)
    assert not bool(found)
    assert grads["w"].shape == (4, 4)


def test_promote_table_matches_jnp_promotion():
    """The PROMOTE list documents apex's promote-to-widest contract for
    mixed-dtype binary ops; assert jnp actually implements it for every
    listed op (otherwise the table is dead documentation)."""
    import importlib

    import jax.numpy as jnp

    from apex_tpu.amp.lists import PROMOTE

    a16 = jnp.ones((2, 2), jnp.bfloat16)
    b32 = jnp.ones((2, 2), jnp.float32)
    for mod_name, fn_name in PROMOTE:
        fn = getattr(importlib.import_module(mod_name), fn_name)
        out = fn(a16, b32)
        if out.dtype == jnp.bool_:
            continue  # comparisons return bool; promotion happened inside
        assert out.dtype == jnp.float32, (mod_name, fn_name, out.dtype)


def test_module_level_amp_surface():
    """Reference parity: amp.scale_loss / amp.state_dict /
    amp.load_state_dict / amp.master_params as MODULE-level functions
    bound to the most recent initialize() (apex keeps the same global
    handle in _amp_state)."""
    import jax
    import jax.numpy as jnp

    import apex_tpu.amp as amp
    from apex_tpu.optimizers import FusedAdam

    params = {"w": jnp.ones((4, 4), jnp.float32)}
    params, opt, handle = amp.initialize(
        params, FusedAdam(lr=1e-3), opt_level="O2", verbosity=0)
    ost = opt.init(params)

    # master_params iterates the fp32 masters (O2 => present)
    masters = list(amp.master_params(ost))
    assert len(masters) == 1 and masters[0].dtype == jnp.float32

    # state_dict round-trips through the module-level functions
    sd = amp.state_dict()
    assert "loss_scaler0" in sd
    amp.load_state_dict(sd)

    # scale_loss delegates to the handle's scaler (functional: returns
    # the scaled loss, the enter half of the reference context manager)
    sst = handle.init_state()
    scaled = amp.scale_loss(jnp.float32(2.0), sst)
    assert float(scaled) == 2.0 * float(sst.loss_scale)

    # O1: no masters
    p1, opt1, _ = amp.initialize(
        {"w": jnp.ones((2,), jnp.float32)}, FusedAdam(lr=1e-3),
        opt_level="O1", verbosity=0)
    assert list(amp.master_params(opt1.init(p1))) == []


def test_o1_cast_cache_contract():
    """Mirror of upstream ``tests/L0/run_amp/test_cache.py`` (SURVEY.md
    §4): apex's O1 cast cache guarantees (a) a weight used by several
    whitelisted ops inside one iteration is cast ONCE, and (b) results
    are identical to explicitly pre-casting the weight. Trace-time
    autocast makes the cache structural — XLA CSE dedupes the repeated
    converts — but the observable contract deserves its own test."""
    w = jnp.ones((8, 8), jnp.float32) * 1.5
    x1 = jnp.ones((4, 8), jnp.float32)
    x2 = jnp.full((4, 8), 2.0, jnp.float32)

    def fn(x1, x2, w):
        with amp.autocast():
            return jnp.matmul(x1, w) + jnp.matmul(x2, w)

    # (b) identical results to the explicit single pre-cast
    expect = (jnp.matmul(x1.astype(jnp.bfloat16), w.astype(jnp.bfloat16))
              + jnp.matmul(x2.astype(jnp.bfloat16), w.astype(jnp.bfloat16)))
    got = jax.jit(fn)(x1, x2, w)
    assert got.dtype == expect.dtype
    assert jnp.array_equal(got, expect)

    # (a) single cast of w in the optimized program: both matmuls read
    # ONE convert of the weight (the cast-cache contract, via CSE)
    hlo = jax.jit(fn).lower(x1, x2, w).compile().as_text()
    import re
    converts = [l for l in hlo.splitlines()
                if re.search(r"convert.*bf16\[8,8\]", l)
                and "f32[8,8]" in l]
    assert len(converts) <= 1, converts


def test_o1_cache_invalidation_across_steps():
    """The second half of the upstream cache test: after a weight
    UPDATE, the next iteration's cast must see the new value (apex
    invalidates the cache each step; here every trace/execution recasts
    by construction). Guards against any future memoization of casts
    across calls."""
    w = jnp.ones((4, 4), jnp.float32)
    x = jnp.ones((2, 4), jnp.float32)

    @jax.jit
    def fwd(x, w):
        with amp.autocast():
            return jnp.matmul(x, w)

    y1 = fwd(x, w)
    w2 = w * 3.0  # optimizer-step analog
    y2 = fwd(x, w2)
    assert jnp.array_equal(y2, y1 * 3.0)
