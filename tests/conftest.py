"""Test harness configuration.

The reference's distributed tests shrink world size onto one node
(SURVEY.md §4); ours go further and run every DP/TP/PP/SyncBN test with no
accelerator at all, on 8 virtual CPU devices. This must happen before the
first JAX backend initialization:

- ``XLA_FLAGS --xla_force_host_platform_device_count=8`` gives 8 CPU devices;
- ``jax.config.update("jax_platforms", "cpu")`` overrides the sandbox's
  axon/TPU plugin (registered by sitecustomize before conftest runs).
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _assert_cpu_sim():
    assert jax.default_backend() == "cpu"
    assert jax.device_count() == 8, "tests expect 8 simulated devices"
