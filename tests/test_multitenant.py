"""Multi-tenant isolation certification (tier-1, CPU): the ISSUE 10
layer (docs/robustness.md, isolation; docs/serving.md, tenancy).

Weighted DRR admission within priority classes (uniform-tenant traffic
bit-identical to the pre-tenancy engine; outputs invariant to tenant
assignment — sampling is arrival-keyed), per-tenant quotas enforced at
the door / admission / block growth with terminal status
``"throttled"``, per-tenant allocator accounting (fractional charge,
eviction/flush attribution), ``abort(uid)`` cancellation with
certified reclamation, streaming delivery, snapshot/restore of the
tenant ledger + mid-DRR-cycle admission walk, and a property-style
fuzz of the admission queue against a naive reference model."""

import dataclasses as dc

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.serving import (
    EngineConfig,
    InferenceEngine,
    QueueFullError,
    Request,
    SamplingParams,
    TenantQuota,
    TenantThrottledError,
)
from apex_tpu.serving.engine import _QueueEntry, _WaitingQueue
from apex_tpu.models import GPTConfig, GPTLMHeadModel


@pytest.fixture(scope="module")
def tiny_gpt():
    cfg = GPTConfig.tiny(dropout=0.0, remat=False)
    model = GPTLMHeadModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    return model, params


ENGINE_KW = dict(max_batch=2, block_size=4, num_blocks=32,
                 max_prefill_len=8, max_seq_len=32, seed=7)


def _mk(tiny_gpt, clock=None, **overrides):
    model, params = tiny_gpt
    kw = dict(ENGINE_KW)
    kw.update(overrides)
    return InferenceEngine(model, params, EngineConfig(**kw),
                           clock=clock)


def _req(uid, seed=0, n=5, new=4, **kw):
    prompt = list(np.random.RandomState(seed).randint(1, 100, n))
    return Request(uid, prompt, max_new_tokens=new, **kw)


def _entry(uid, tenant="default", priority=0, n=5, new=5, charged=False,
           seed=None):
    prompt = list(np.random.RandomState(
        seed if seed is not None else abs(hash(uid)) % 1000).randint(
            1, 100, n))
    return _QueueEntry(request=Request(uid, prompt, max_new_tokens=new,
                                       tenant=tenant, priority=priority),
                       drr_charged=charged)


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------


def test_tenancy_config_validation():
    good = dict(max_batch=2, block_size=4, num_blocks=16,
                max_prefill_len=8, max_seq_len=16)
    with pytest.raises(ValueError, match="tenant_weights"):
        EngineConfig(**good, tenant_weights={"a": 0})
    with pytest.raises(ValueError, match="drr_quantum"):
        EngineConfig(**good, drr_quantum=0)
    with pytest.raises(ValueError, match="tenant_rate_tau_s"):
        EngineConfig(**good, tenant_rate_tau_s=0.0)
    with pytest.raises(ValueError, match="max_waiting"):
        EngineConfig(**good, tenant_quotas={"a": TenantQuota(max_waiting=0)})
    with pytest.raises(ValueError, match="max_resident_blocks"):
        EngineConfig(**good, tenant_quotas={
            "a": TenantQuota(max_resident_blocks=0)})
    with pytest.raises(ValueError, match="tokens_per_s"):
        EngineConfig(**good, tenant_quotas={
            "a": TenantQuota(tokens_per_s=0.0)})
    with pytest.raises(ValueError, match="TenantQuota"):
        EngineConfig(**good, tenant_quotas={"a": {"max_waiting": 1}})
    with pytest.raises(ValueError, match="spec_adapt"):
        EngineConfig(**good, spec_adapt=True)
    with pytest.raises(ValueError, match="low"):
        EngineConfig(**good, spec_tokens=4, spec_adapt=True,
                     spec_accept_low=0.9, spec_accept_high=0.5)


def test_add_request_rejects_bad_tenant(tiny_gpt):
    engine = _mk(tiny_gpt)
    with pytest.raises(ValueError, match="tenant"):
        engine.add_request(_req("a", tenant=""))


# ---------------------------------------------------------------------------
# uniform-tenant bit-identity + tenant-assignment invariance
# ---------------------------------------------------------------------------


def _mixed_reqs(tag="r", tenants=None, n_req=6):
    """Staggered greedy+sampled requests; small pool forces
    preemptions so the certification covers the interesting paths."""
    out = []
    for i in range(n_req):
        kw = {}
        if tenants is not None:
            kw["tenant"] = tenants[i % len(tenants)]
        out.append(_req(
            f"{tag}{i}", seed=i, n=4 + i % 3, new=3 + (i % 3) * 2,
            priority=i % 2,
            sampling=(SamplingParams(temperature=1.0, top_k=13)
                      if i % 3 == 0 else SamplingParams()),
            **kw))
    return out


def test_single_tenant_traffic_bit_identical_to_default(tiny_gpt):
    """All requests under ONE tenant id — at any weight — must produce
    the identical schedule AND outputs as the untagged engine (the
    PR 8 behavior): DRR over a single tenant degenerates to the
    per-class FIFO."""
    runs = []
    for weights, tenant in ((None, None), ({"solo": 5}, "solo")):
        engine = _mk(tiny_gpt, num_blocks=12,
                     tenant_weights=weights, drr_quantum=3)
        reqs = _mixed_reqs(tenants=[tenant] if tenant else None)
        for r in reqs[:4]:
            engine.add_request(r)
        engine.step(); engine.step()
        for r in reqs[4:]:
            engine.add_request(r)
        out = engine.run()
        stats = engine.stats()
        runs.append((out, stats["num_preemptions"],
                     stats["num_decode_dispatches"],
                     stats["num_prefill_chunks"]))
    assert runs[0][0] == runs[1][0]          # outputs bit-identical
    assert runs[0][1:] == runs[1][1:]        # and the SCHEDULE matches


def test_outputs_invariant_to_tenant_assignment(tiny_gpt):
    """Scattering the same requests across tenants (with weights —
    admission ORDER genuinely changes) must not change any request's
    tokens: sampling is arrival-keyed."""
    base = None
    for tenants in (None, ("a", "b", "c")):
        engine = _mk(tiny_gpt, num_blocks=12,
                     tenant_weights={"a": 3} if tenants else None,
                     drr_quantum=4)
        for r in _mixed_reqs(tenants=tenants):
            engine.add_request(r)
        out = engine.run()
        if base is None:
            base = out
        else:
            assert out == base
    engine.check_allocator_integrity()


# ---------------------------------------------------------------------------
# the DRR walk (queue level)
# ---------------------------------------------------------------------------


def test_drr_weighted_fairness_pop_order():
    q = _WaitingQueue(weights={"a": 2, "b": 1}, quantum=10)
    for i in range(6):
        q.append(_entry(f"a{i}", tenant="a", n=5, new=5))   # cost 10
        q.append(_entry(f"b{i}", tenant="b", n=5, new=5))
    order = [q.popleft().request.uid for _ in range(9)]
    # weight 2:1 in committed tokens -> a serves two for b's one
    assert order == ["a0", "a1", "b0", "a2", "a3", "b1", "a4", "a5",
                     "b2"]


def test_drr_strict_priority_between_classes():
    q = _WaitingQueue(weights={"a": 1, "b": 8}, quantum=10)
    q.append(_entry("b-low", tenant="b", priority=1))
    q.append(_entry("a-hi", tenant="a", priority=0))
    # class 0 drains first no matter the weights: strict priority
    # between classes is the documented contract
    assert q.popleft().request.uid == "a-hi"
    assert q.popleft().request.uid == "b-low"


def test_drr_charged_entries_serve_first_and_free():
    q = _WaitingQueue(weights={"a": 1, "b": 1}, quantum=10)
    for i in range(2):
        q.append(_entry(f"a{i}", tenant="a", n=5, new=5))
        q.append(_entry(f"b{i}", tenant="b", n=5, new=5))
    assert q.popleft().request.uid == "a0"
    # a preemption requeue (charged) for b jumps the whole walk
    q.appendleft(_entry("b-resume", tenant="b", charged=True))
    assert q.popleft().request.uid == "b-resume"
    # ...and consumed no deficit and moved no cursor: the walk resumes
    # exactly where it was (1:1 weights alternate, so b0 then a1 —
    # identical to the order WITHOUT the charged insert)
    assert q.popleft().request.uid == "b0"
    assert q.popleft().request.uid == "a1"


def test_drr_head_matches_popleft_with_skip():
    q = _WaitingQueue(weights={"a": 4}, quantum=10)
    for t in ("a", "b", "c"):
        for i in range(2):
            q.append(_entry(f"{t}{i}", tenant=t))
    for skip in (None, {"a"}, {"a", "b"}, {"a", "b", "c"}):
        h = q.head(skip=skip)
        if h is None:
            with pytest.raises(IndexError):
                q.popleft(skip=skip)
            continue
        assert q.popleft(skip=skip) is h
        assert h.request.tenant not in (skip or ())


# ---------------------------------------------------------------------------
# satellite: property-style fuzz vs a naive reference model
# ---------------------------------------------------------------------------


class _RefModel:
    """The naive reference: per-(class, tenant) FIFO lists plus the
    declarative properties the real queue must satisfy — no deques, no
    incremental counters, everything recomputed from scratch."""

    def __init__(self):
        self.lanes = {}        # (priority, tenant) -> [uid, ...]

    def add(self, entry, left=False):
        lane = self.lanes.setdefault(
            (entry.request.priority, entry.request.tenant), [])
        lane.insert(0, entry.request.uid) if left else \
            lane.append(entry.request.uid)

    def remove(self, uid):
        for lane in self.lanes.values():
            if uid in lane:
                lane.remove(uid)

    def size(self):
        return sum(len(v) for v in self.lanes.values())

    def min_class(self):
        live = [p for (p, _), lane in self.lanes.items() if lane]
        return min(live) if live else None

    def lane_head(self, priority, tenant):
        lane = self.lanes.get((priority, tenant), [])
        return lane[0] if lane else None

    def tenant_depth(self, tenant):
        return sum(len(lane) for (p, t), lane in self.lanes.items()
                   if t == tenant)


def test_queue_fuzz_against_reference_model():
    rng = np.random.RandomState(1234)
    q = _WaitingQueue(weights={"t0": 3, "t1": 1}, quantum=7)
    ref = _RefModel()
    uid_counter = [0]

    def fresh_entry(left=False):
        t = f"t{rng.randint(3)}"
        e = _entry(f"u{uid_counter[0]}", tenant=t,
                   priority=int(rng.randint(3)),
                   n=int(rng.randint(1, 8)), new=int(rng.randint(1, 8)),
                   charged=bool(left and rng.randint(2)))
        uid_counter[0] += 1
        return e

    for _ in range(400):
        op = rng.randint(5)
        if op == 0 or len(q) == 0:                       # append
            e = fresh_entry()
            q.append(e)
            ref.add(e)
        elif op == 1:                                    # requeue
            e = fresh_entry(left=True)
            q.appendleft(e)
            ref.add(e, left=True)
        elif op == 2:                                    # pop
            h = q.head()
            e = q.popleft()
            assert e is h                     # head == popleft, always
            r = e.request
            # strict priority: always the most urgent nonempty class
            assert r.priority == ref.min_class()
            # FIFO within the (class, tenant) lane
            assert ref.lane_head(r.priority, r.tenant) == r.uid
            assert e.drr_charged        # charged exactly at service
            ref.remove(r.uid)
        elif op == 3:                                    # expel
            victim = f"u{rng.randint(max(uid_counter[0], 1))}"
            removed = q.expel(lambda e: e.request.uid == victim)
            assert len(removed) in (0, 1)
            for e in removed:
                ref.remove(e.request.uid)
        else:                                            # audit tick
            pass
        # global invariants, every step
        assert len(q) == ref.size()
        assert {e.request.uid for e in q} == {
            u for lane in ref.lanes.values() for u in lane}
        for t in ("t0", "t1", "t2"):
            assert q.tenant_depth(t) == ref.tenant_depth(t)
    # drain completely: every entry must come out exactly once
    remaining = ref.size()
    seen = set()
    while len(q):
        seen.add(q.popleft().request.uid)
    assert len(seen) == remaining
    assert seen == {u for lane in ref.lanes.values() for u in lane}


def test_drr_serves_costs_far_above_the_quantum():
    """A committed budget many quanta deep must be served, not trip
    the walk's termination guard (each credit costs two loop
    iterations — the bound must cover that)."""
    q = _WaitingQueue(quantum=64)
    q.append(_entry("huge", n=600, new=128))
    assert q.head().request.uid == "huge"
    assert q.popleft().request.uid == "huge"
    q = _WaitingQueue(weights={"a": 1, "b": 2}, quantum=16)
    for i in range(3):
        q.append(_entry(f"a{i}", tenant="a", n=400, new=100))
        q.append(_entry(f"b{i}", tenant="b", n=400, new=100))
    served = [q.popleft().request.uid for _ in range(6)]
    assert set(served) == {f"{t}{i}" for t in "ab" for i in range(3)}


def test_drr_long_run_share_tracks_weights():
    """Backlogged tenants with weights 3:1 must be served committed
    token volume in ~3:1 (the fairness property, not just the exact
    small-case order)."""
    q = _WaitingQueue(weights={"a": 3, "b": 1}, quantum=8)
    for i in range(120):
        q.append(_entry(f"a{i}", tenant="a", n=4, new=4))    # cost 8
        q.append(_entry(f"b{i}", tenant="b", n=4, new=4))
    served = {"a": 0, "b": 0}
    for _ in range(120):
        served[q.popleft().request.tenant] += 1
    ratio = served["a"] / max(served["b"], 1)
    assert 2.5 <= ratio <= 3.5, served


def test_engine_lifecycle_fuzz_live_uid_consistency(tiny_gpt):
    """Random interleavings of add / try_add / abort / step / expire
    across tenants and priorities: the live-uid set must always equal
    waiting + resident uids, the queue bound must hold for client
    adds, and every accepted request must end terminal."""
    t = [0.0]
    engine = _mk(tiny_gpt, num_blocks=16, max_waiting=6,
                 clock=lambda: t[0],
                 tenant_weights={"x": 2},
                 tenant_quotas={"z": TenantQuota(max_waiting=2)})
    rng = np.random.RandomState(99)
    accepted, k = set(), 0
    for _ in range(90):
        op = rng.randint(6)
        if op <= 1:
            uid = f"f{k}"; k += 1
            ok = engine.try_add(_req(
                uid, seed=k, n=int(rng.randint(2, 7)),
                new=int(rng.randint(1, 5)),
                tenant=f"{'xyz'[rng.randint(3)]}",
                priority=int(rng.randint(2)),
                deadline_s=(None if rng.randint(3) else 5.0)))
            if ok:
                accepted.add(uid)
        elif op == 2 and accepted:
            uid = sorted(accepted)[rng.randint(len(accepted))]
            engine.abort(uid)
        elif op == 3:
            t[0] += float(rng.rand())
            engine.step()
        else:
            engine.step()
        waiting_uids = {e.request.uid for e in engine.waiting}
        resident_uids = {s.request.uid for s in engine.slots
                         if s is not None}
        assert engine._live_uids == waiting_uids | resident_uids
        assert len(engine.waiting) <= 6 + 2     # bound + requeue slack
    res = engine.run(return_status=True)
    # every accepted request reached a terminal verdict exactly once
    assert accepted <= set(res)
    assert all(r.status in ("finished", "timeout", "failed",
                            "cancelled", "rejected", "throttled")
               for r in res.values())
    engine.check_allocator_integrity()


# ---------------------------------------------------------------------------
# quotas
# ---------------------------------------------------------------------------


def test_throttle_per_tenant_max_waiting(tiny_gpt):
    engine = _mk(tiny_gpt, tenant_quotas={"f": TenantQuota(max_waiting=2)})
    engine.add_request(_req("f0", tenant="f"))
    engine.add_request(_req("f1", seed=1, tenant="f"))
    with pytest.raises(TenantThrottledError, match="max_waiting"):
        engine.add_request(_req("f2", seed=2, tenant="f"))
    # OTHER tenants are untouched by f's quota
    engine.add_request(_req("g0", seed=3, tenant="g"))
    assert engine.try_add(_req("f3", seed=4, tenant="f")) is False
    res = engine.run(return_status=True)
    assert res["f2"].status == "throttled"
    assert res["f3"].status == "throttled"
    assert res["f2"].tokens == []
    assert {res[u].status for u in ("f0", "f1", "g0")} == {"finished"}
    assert engine.stats()["num_throttled"] == 2


def test_throttle_token_rate_budget(tiny_gpt):
    t = [0.0]
    engine = _mk(tiny_gpt, clock=lambda: t[0], tenant_rate_tau_s=2.0,
                 tenant_quotas={"f": TenantQuota(tokens_per_s=3.0)})
    engine.add_request(_req("f0", tenant="f", new=8))
    out = engine.run()
    assert len(out["f0"]) == 8
    # 8 tokens at t=0 -> estimator 8/tau = 4.0 > 3.0: over budget
    with pytest.raises(TenantThrottledError, match="token-rate"):
        engine.add_request(_req("f1", seed=1, tenant="f"))
    # an unquota'd tenant at the same instant is fine
    engine.add_request(_req("g0", seed=2, tenant="g"))
    # after decay the budget recovers: rate 4 * exp(-4/2) ~ 0.54
    t[0] += 4.0
    engine.add_request(_req("f2", seed=3, tenant="f"))
    res = engine.run(return_status=True)
    assert res["f2"].status == "finished"
    rate = engine.stats()["tenants"]["f"]["rate_tokens_per_s"]
    assert rate > 0.0


def test_throttle_impossible_footprint_at_door(tiny_gpt):
    # worst case blocks_needed(6 + 20, 4) = 7 > cap 3: can never run
    engine = _mk(tiny_gpt,
                 tenant_quotas={"f": TenantQuota(max_resident_blocks=3)})
    with pytest.raises(TenantThrottledError, match="never run"):
        engine.add_request(_req("f0", tenant="f", n=6, new=20))
    # within the ceiling is accepted and runs
    engine.add_request(_req("f1", seed=1, tenant="f", n=6, new=4))
    assert engine.run(return_status=True)["f1"].status == "finished"


def test_block_quota_holds_tenant_not_class(tiny_gpt):
    """A tenant at its block ceiling is SKIPPED by admission while its
    lanes drain — another tenant in the same class flows past it."""
    engine = _mk(tiny_gpt, max_batch=2, num_blocks=32,
                 tenant_quotas={"f": TenantQuota(max_resident_blocks=3)})
    # f0 occupies ~3 blocks (prompt 6 + up to 4 new -> ceil(10/4)=3)
    engine.add_request(_req("f0", tenant="f", n=6, new=4))
    engine.add_request(_req("f1", seed=1, tenant="f", n=6, new=4))
    engine.add_request(_req("v0", seed=2, tenant="v", n=6, new=4))
    engine.step()
    # one lane holds f0; f1 must NOT take the second lane (quota),
    # v0 must: the hold is per-tenant, not head-of-line
    resident = {s.request.uid for s in engine.slots if s is not None}
    assert resident == {"f0", "v0"}
    res = engine.run(return_status=True)
    assert {r.status for r in res.values()} == {"finished"}
    charge = engine.stats()["tenants"]["f"]["resident_block_charge"]
    assert charge == 0.0    # drained


def test_block_quota_growth_preempts_own_lane(tiny_gpt):
    """Decode-time growth past the tenant's ceiling preempts the
    tenant's OWN youngest lane — the victim tenant's lane survives."""
    engine = _mk(tiny_gpt, max_batch=3, num_blocks=32, decode_steps=4,
                 tenant_quotas={"f": TenantQuota(max_resident_blocks=4)})
    engine.add_request(_req("f0", tenant="f", n=7, new=9))   # grows
    engine.add_request(_req("f1", seed=1, tenant="f", n=7, new=9))
    engine.add_request(_req("v0", seed=2, tenant="v", n=7, new=9))
    seen_preempt = False
    while engine.has_work:
        engine.step()
        resident = {s.request.uid for s in engine.slots if s is not None}
        if engine.stats()["tenants"]["f"]["quota_preemptions"] > 0:
            seen_preempt = True
            assert "v0" in resident or "v0" in engine.finished
    assert seen_preempt
    res = engine.run(return_status=True)
    assert {r.status for r in res.values()} == {"finished"}
    # outputs unaffected by the quota-preemption schedule
    base = _mk(tiny_gpt, max_batch=3, num_blocks=32, decode_steps=4)
    for r in (_req("f0", n=7, new=9), _req("f1", seed=1, n=7, new=9),
              _req("v0", seed=2, n=7, new=9)):
        base.add_request(r)
    assert {u: r.tokens for u, r in res.items()} == base.run()


# ---------------------------------------------------------------------------
# abort
# ---------------------------------------------------------------------------


def test_abort_waiting_and_unknown(tiny_gpt):
    engine = _mk(tiny_gpt)
    engine.add_request(_req("a"))
    engine.add_request(_req("b", seed=1))
    assert engine.abort("b") is True
    assert engine.abort("b") is False        # already terminal
    assert engine.abort("nope") is False     # unknown
    res = engine.run(return_status=True)
    assert res["b"].status == "cancelled"
    assert res["b"].tokens == []
    assert res["a"].status == "finished"
    # the uid is reusable after drain, like any terminal exit
    engine.add_request(_req("b", seed=2))
    assert engine.run(return_status=True)["b"].status == "finished"


def test_abort_resident_reclaims_blocks(tiny_gpt):
    engine = _mk(tiny_gpt, max_batch=2)
    engine.add_request(_req("a", new=10))
    engine.add_request(_req("b", seed=1, new=10))
    engine.step()            # both admitted, prefilling
    engine.step()
    resident = {s.request.uid for s in engine.slots if s is not None}
    assert "a" in resident
    free_before = engine.allocator.num_free
    assert engine.abort("a") is True
    assert engine.allocator.num_free > free_before
    engine.check_allocator_integrity()
    res = engine.run(return_status=True)
    assert res["a"].status == "cancelled"
    assert res["b"].status == "finished"
    assert len(res["b"].tokens) == 10


def test_abort_mid_flight_discards_lane_results(tiny_gpt):
    """Abort a STARTED lane while its decode dispatch is in flight:
    the deferred drain must discard that lane's tokens (matching by
    uid), the request keeps only what it had, and a new request
    admitted into the freed lane is unharmed."""
    engine = _mk(tiny_gpt, max_batch=2, decode_steps=4)
    engine.add_request(_req("a", new=12))
    engine.add_request(_req("b", seed=1, new=12))
    while engine._pending is None or len(engine._pending[1]) < 2:
        engine.step()
    # the dispatch is in flight over both lanes: abort one now
    covered = set(engine._pending[2].values())
    assert covered == {"a", "b"}
    pre_tokens = dict(engine.finished)
    assert engine.abort("a") is True
    a_tokens_at_abort = engine.finished["a"]
    engine.add_request(_req("c", seed=2, new=4))
    res = engine.run(return_status=True)
    assert res["a"].status == "cancelled"
    assert res["a"].tokens == a_tokens_at_abort   # nothing post-abort
    assert len(res["a"].tokens) < 12
    assert res["b"].status == "finished"
    assert len(res["b"].tokens) == 12
    assert res["c"].status == "finished"
    engine.check_allocator_integrity()
    # determinism: the surviving lanes' outputs match an abort-free run
    base = _mk(tiny_gpt, max_batch=2, decode_steps=4)
    base.add_request(_req("b", seed=1, new=12))
    assert base.run()["b"] == res["b"].tokens


# ---------------------------------------------------------------------------
# streaming
# ---------------------------------------------------------------------------


def test_streaming_matches_run_and_sentinels_once(tiny_gpt):
    engine = _mk(tiny_gpt,
                 tenant_quotas={"f": TenantQuota(max_waiting=1)})
    reqs = [_req("s0", new=5), _req("s1", seed=1, new=3,
                                    sampling=SamplingParams(
                                        temperature=1.0, top_k=17))]
    for r in reqs:
        engine.add_request(r)
    engine.add_request(_req("f0", seed=2, tenant="f"))
    with pytest.raises(TenantThrottledError):
        engine.add_request(_req("f1", seed=3, tenant="f"))
    events = []
    while engine.has_work:
        engine.step()
        events += engine.pop_stream_events()
    events += engine.pop_stream_events()
    assert engine.stats()["stream_backlog"] == 0
    res = engine.run(return_status=True)
    # per-uid token streams reassemble the run() results exactly
    for uid, r in res.items():
        toks = [t for u, t, last in events if u == uid and not last]
        assert toks == r.tokens, uid
    # exactly one terminal sentinel per request, -1 payload, ordered
    # after every token of its uid
    for uid in res:
        lasts = [i for i, (u, t, last) in enumerate(events)
                 if u == uid and last]
        assert len(lasts) == 1, uid
        assert events[lasts[0]][1] == -1
        tok_idx = [i for i, (u, t, last) in enumerate(events)
                   if u == uid and not last]
        assert all(i < lasts[0] for i in tok_idx)
    # throttled-at-door still announces termination on the stream
    assert res["f1"].status == "throttled"


def test_streaming_does_not_replay_resumed_history(tiny_gpt):
    """Preempted requests resume carrying their tokens — the stream
    must emit each token ONCE even across preempt/resume."""
    engine = _mk(tiny_gpt, max_batch=2, num_blocks=6, decode_steps=2)
    for i in range(4):
        engine.add_request(_req(f"p{i}", seed=i, n=6, new=8))
    events = []
    while engine.has_work:
        engine.step()
        events += engine.pop_stream_events()
    events += engine.pop_stream_events()
    assert engine.stats()["num_preemptions"] > 0   # the point
    res = engine.run(return_status=True)
    for uid, r in res.items():
        toks = [t for u, t, last in events if u == uid and not last]
        assert toks == r.tokens, uid


# ---------------------------------------------------------------------------
# snapshot / restore
# ---------------------------------------------------------------------------


def _record_admissions(engine):
    order = []
    orig = engine._note_admitted_wait

    def wrapped(entry):
        order.append(entry.request.uid)
        return orig(entry)   # pass the (wait_s, admit_t) pair through

    engine._note_admitted_wait = wrapped
    return order


def test_snapshot_mid_drr_cycle_restores_admission_walk(tiny_gpt):
    """THE acceptance bar: snapshot while the DRR walk is mid-cycle;
    the restored engine must admit the remaining waiting entries in
    the identical order (and produce identical outputs)."""
    kw = dict(max_batch=2, num_blocks=32, drr_quantum=5,
              tenant_weights={"x": 2, "y": 1, "z": 1})
    reqs = [_req(f"{t}{j}", seed=7 * i + j, n=4 + j, new=3,
                 tenant=t,
                 sampling=(SamplingParams(temperature=1.0, top_k=11)
                           if j % 2 else SamplingParams()))
            for i, t in enumerate(("x", "y", "z"))
            for j in range(3)]
    a = _mk(tiny_gpt, **kw)
    a_order = _record_admissions(a)
    for r in reqs:
        a.add_request(r)
    while a._admit_count < 3:
        a.step()
    n_at_snap = len(a_order)
    resident_at_snap = {s.request.uid for s in a.slots if s is not None}
    snap = a.snapshot()
    out_a = a.run()                      # the uninterrupted run

    b = _mk(tiny_gpt, **kw)
    b_order = _record_admissions(b)
    b.restore(snap)
    out_b = b.run()
    # identical outputs (sampled lanes included)...
    assert out_b == out_a
    # ...and the identical admission walk: modulo the residents that
    # restore re-admits (charged, out of band), the restored engine
    # admits the same uids in the same order
    b_fresh = [u for u in b_order if u not in resident_at_snap]
    assert b_fresh == a_order[n_at_snap:]


def test_snapshot_roundtrip_tenant_ledger(tiny_gpt):
    t = [0.0]
    kw = dict(tenant_weights={"a": 2},
              tenant_quotas={"f": TenantQuota(max_waiting=1)})
    a = _mk(tiny_gpt, clock=lambda: t[0], **kw)
    a.add_request(_req("a0", tenant="a", new=3))
    a.add_request(_req("f0", seed=1, tenant="f", new=3))
    with pytest.raises(TenantThrottledError):
        a.add_request(_req("f1", seed=2, tenant="f"))
    a.abort("f0")
    a.add_request(_req("a1", seed=3, tenant="a", new=3))
    for _ in range(3):
        a.step()
    snap = a.snapshot()

    b = _mk(tiny_gpt, clock=lambda: t[0], **kw)
    b.restore(snap)
    out = b.run(return_status=True)
    assert out["a0"].status == "finished"
    sa, sb = snap["tenancy"], b.snapshot()["tenancy"]
    ts = b.stats()["tenants"]
    assert ts["f"]["statuses"] == {"throttled": 1, "cancelled": 1}
    # delivered-token ledger carried over and kept counting
    assert ts["a"]["tokens"] >= sa["tokens"].get("a", 0)
    assert b.stats()["num_restores"] == 1
    b.check_allocator_integrity()


def test_stats_tenant_section_shape(tiny_gpt):
    # "acme" is LISTED (a weight entry), so its ledger row is
    # permanent; unlisted tenants prune once idle (next test)
    engine = _mk(tiny_gpt, tenant_weights={"acme": 2})
    engine.add_request(_req("a0", tenant="acme"))
    engine.run()
    ts = engine.stats()["tenants"]
    assert set(ts) >= {"acme", "default"}
    row = ts["acme"]
    for key in ("tokens", "rate_tokens_per_s", "waiting",
                "resident_slots", "resident_block_charge",
                "cached_blocks", "evicted_blocks", "flushed_blocks",
                "quota_preemptions", "statuses"):
        assert key in row, key
    assert row["tokens"] == 4
    assert row["statuses"] == {"finished": 1}


def test_unlisted_idle_tenants_are_pruned(tiny_gpt):
    """tenant is a free-form client string: an adversary minting a
    fresh id per request must not grow the ledger without bound.
    Unlisted tenants drop from the ledger once they have no waiting or
    resident footprint; listed ones (weights/quotas) are permanent."""
    engine = _mk(tiny_gpt, tenant_weights={"keep": 1})
    for i in range(6):
        engine.add_request(_req(f"e{i}", seed=i, tenant=f"ephemeral-{i}"))
    engine.add_request(_req("k", seed=9, tenant="keep"))
    engine.run()
    ts = engine.stats()["tenants"]
    assert "keep" in ts and ts["keep"]["tokens"] == 4
    assert not any(t.startswith("ephemeral-") for t in ts), set(ts)
    # while live, the row IS there (observability before the drain)
    engine.add_request(_req("e9", seed=10, tenant="ephemeral-9"))
    assert "ephemeral-9" in engine.stats()["tenants"]
    engine.run()
    assert "ephemeral-9" not in engine.stats()["tenants"]


def test_match_prefix_is_tenant_scoped():
    from apex_tpu.serving import BlockAllocator, hash_block_tokens
    a = BlockAllocator(8)
    b = a.alloc(1, tenant="acme")[0]
    h = hash_block_tokens(None, [1, 2, 3, 4])
    a.register_prefix(h, b, tenant="acme")
    a.free([b], tenant="acme")           # retained, cached
    got = a.match_prefix([h], tenant="bolt")
    assert got == [b]
    a.free(got, tenant="bolt")           # the same tenant releases it
    a.check_integrity()


def test_prefix_flush_charges_registering_tenant(tiny_gpt):
    """Rung-2 flushes / LRU evictions are attributed to the tenant
    that parked the blocks in the prefix cache."""
    engine = _mk(tiny_gpt, enable_prefix_caching=True, num_blocks=16)
    engine.add_request(_req("a0", tenant="hog", n=8, new=2))
    engine.run()
    assert engine.stats()["tenants"]["hog"]["cached_blocks"] > 0
    flushed = engine.allocator.flush_evictable()
    assert flushed > 0
    assert (engine.stats()["tenants"]["hog"]["flushed_blocks"]
            == flushed)
