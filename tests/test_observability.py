"""The observability layer (ISSUE 11, docs/observability.md): the
metrics registry / flight recorder / request tracer units, the shared
percentile helper's pinned interpolation, trace-export validity, the
offline summarizer, and — the acceptance bar — the zero-perturbation
certification: engine outputs with tracing/recorder/metrics attached
are bit-identical to without, across greedy/sampled x speculative/not
x preemption x snapshot/restore."""

import importlib.util
import json
import math
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import profiler
from apex_tpu.models import GPTConfig, GPTLMHeadModel
from apex_tpu.observability import (
    RECORDER_EVENT_KINDS,
    TRACE_EVENT_TYPES,
    FlightRecorder,
    Histogram,
    MetricsRegistry,
    Observability,
    RequestTracer,
    flatten_stats,
    log_buckets,
    percentile,
)
from apex_tpu.serving import (
    EngineConfig,
    EngineStalledError,
    InferenceEngine,
    Request,
    SamplingParams,
)
from apex_tpu.train.loop import TrainLoop, WatchdogConfig
from apex_tpu.utils.faults import FaultPlan, FaultSpec, SimulatedCrash

# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_gpt():
    cfg = GPTConfig.tiny(dropout=0.0, remat=False)
    model = GPTLMHeadModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    return model, params


# a pool tight enough to force preemption under 3 concurrent
# generations (the test_serving tight-pool recipe)
TIGHT_KW = dict(max_batch=3, block_size=8, num_blocks=6,
                max_prefill_len=8, max_seq_len=32, seed=7)


def _mk(tiny_gpt, obs=None, clock=None, **overrides):
    model, params = tiny_gpt
    kw = dict(TIGHT_KW)
    kw.update(overrides)
    return InferenceEngine(model, params, EngineConfig(**kw),
                           obs=obs, clock=clock)


def _reqs(n=3, new=14, sampled=False, seed=11):
    rng = np.random.RandomState(seed)
    sp = (SamplingParams(temperature=1.0, top_k=20) if sampled
          else SamplingParams())
    return [Request(uid=f"r{i}", prompt=list(rng.randint(0, 128, 6 + i)),
                    max_new_tokens=new, sampling=sp) for i in range(n)]


def _results_key(res):
    return {u: (tuple(r.tokens), r.status) for u, r in res.items()}


# ---------------------------------------------------------------------------
# the shared percentile helper (satellite: pinned interpolation)
# ---------------------------------------------------------------------------


def test_percentile_pins_numpy_linear_interpolation():
    cases = [[3.0], [1.0, 2.0], [5, 1, 9, 2], [7, 3, 3, 1, 8],
             list(range(100, 0, -1))]
    for xs in cases:
        for q in (0, 10, 25, 50, 75, 90, 99, 100):
            assert percentile(xs, q) == pytest.approx(
                float(np.percentile(xs, q)), abs=1e-12), (xs, q)
    # the even-n median the old StepTimer got wrong: ts[n // 2] of
    # [1, 2, 3, 4] is 3.0; the median is 2.5
    assert percentile([1.0, 2.0, 3.0, 4.0], 50) == 2.5
    with pytest.raises(ValueError):
        percentile([], 50)
    with pytest.raises(ValueError):
        percentile([1.0], 101)


def test_step_timer_uses_interpolated_percentiles():
    t = profiler.StepTimer(warmup=0)
    t._times = [0.001, 0.002, 0.003, 0.004]   # even n
    s = t.summary()
    assert s["p50_ms"] == pytest.approx(2.5)
    assert s["p90_ms"] == pytest.approx(
        1e3 * float(np.percentile(t._times, 90)))
    assert s["p99_ms"] == pytest.approx(
        1e3 * float(np.percentile(t._times, 99)))
    assert s["steps"] == 4 and s["min_ms"] <= s["p50_ms"] <= s["max_ms"]


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


def test_log_buckets_geometry():
    b = log_buckets(1e-3, 1.0, 4)
    assert b[0] == pytest.approx(1e-3) and b[-1] == pytest.approx(1.0)
    ratios = [b[i + 1] / b[i] for i in range(3)]
    assert all(r == pytest.approx(ratios[0]) for r in ratios)
    with pytest.raises(ValueError):
        log_buckets(1.0, 0.5, 4)


def test_histogram_observe_quantile_exposition():
    h = Histogram("h_s", "help", buckets=(0.001, 0.01, 0.1, 1.0))
    for v in (0.0005, 0.005, 0.005, 0.05, 5.0):
        h.observe(v)
    assert h.count == 5 and h.sum == pytest.approx(5.0605)
    assert h.counts == [1, 2, 1, 0, 1]       # last is the +Inf bucket
    lines = h.expose()
    assert 'h_s_bucket{le="0.001"} 1' in lines
    assert 'h_s_bucket{le="0.01"} 3' in lines     # cumulative
    assert 'h_s_bucket{le="+Inf"} 5' in lines
    assert "h_s_count 5" in lines
    # quantile estimate lands inside the right bucket
    assert 0.001 <= h.quantile(50) <= 0.01 + 1e-12
    assert Histogram("e", "").quantile(99) == 0.0


def test_registry_exposition_and_get_or_create():
    r = MetricsRegistry()
    c = r.counter("a_total", "things")
    c.inc()
    c.inc(2)
    assert r.counter("a_total") is c          # get-or-create
    g = r.gauge("g")
    g.set(1.5)
    with pytest.raises(ValueError):
        r.gauge("a_total")                    # kind clash
    with pytest.raises(ValueError):
        c.inc(-1)                             # counters only go up
    text = r.exposition()
    assert "# TYPE a_total counter" in text
    assert "a_total 3" in text
    assert "g 1.5" in text
    assert r.as_dict()["a_total"] == 3


def test_flatten_stats_is_the_sanctioned_flattener():
    nested = {"a": 1, "b": {"x": 2.0, "y": {"z": "s"}}, "tenants": {"t": 1}}
    flat = flatten_stats(nested)
    assert flat == {"a": 1, "b.x": 2.0, "b.y.z": "s", "tenants.t": 1}
    assert flatten_stats(nested, exclude=("tenants",)) == {
        "a": 1, "b.x": 2.0, "b.y.z": "s"}


def test_engine_stats_type_honesty_and_flattening(tiny_gpt):
    """Satellite: stats() is annotated Dict[str, object] because it
    really does nest (the per-tenant ledger); the sanctioned flattener
    turns it scalar."""
    engine = _mk(tiny_gpt)
    stats = engine.stats()
    assert isinstance(stats["tenants"], dict)        # the nested section
    flat = flatten_stats(stats, exclude=("tenants",))
    assert all(not isinstance(v, dict) for v in flat.values())
    assert "tenants" not in " ".join(flat)


# ---------------------------------------------------------------------------
# flight recorder + tracer units
# ---------------------------------------------------------------------------


def test_recorder_ring_bound_dropped_and_incidents():
    now = [0.0]
    rec = FlightRecorder(capacity=4, clock=lambda: now[0])
    with pytest.raises(ValueError):
        rec.record("not_a_kind")
    for i in range(10):
        now[0] = float(i)
        rec.record("tick", tick=i)
    assert len(rec) == 4 and rec.dropped == 6
    tail = rec.tail(2)
    assert [e["tick"] for e in tail] == [8, 9]
    assert [e["seq"] for e in rec.tail()] == [6, 7, 8, 9]
    inc = rec.incident("quarantine", uid="x")
    assert inc["label"] == "quarantine" and len(inc["events"]) == 4
    assert len(rec.incidents) == 1
    d = rec.dump()
    json.loads(json.dumps(d))
    assert d["dropped"] == 6 and len(d["incidents"]) == 1


def test_tracer_rejects_unknown_types_and_caps():
    tr = RequestTracer(clock=lambda: 0.0, max_events=2)
    with pytest.raises(ValueError):
        tr.event("not_a_type", "u")
    tr.event("enqueue", "u")
    tr.event("admit", "u", lane=0)
    tr.event("terminal", "u", lane=0, status="finished")   # over cap
    assert len(tr) == 2 and tr.dropped == 1
    assert [e["type"] for e in tr.request_timeline("u")] == [
        "enqueue", "admit"]


def test_vocabularies_are_closed_and_exported():
    assert "decode" in TRACE_EVENT_TYPES
    assert "device_reset" in RECORDER_EVENT_KINDS
    assert len(set(TRACE_EVENT_TYPES)) == len(TRACE_EVENT_TYPES)
    assert len(set(RECORDER_EVENT_KINDS)) == len(RECORDER_EVENT_KINDS)


# ---------------------------------------------------------------------------
# trace export validity (satellite)
# ---------------------------------------------------------------------------


def _fake_clock():
    now = [0.0]

    def clock():
        return now[0]

    return now, clock


def _drive_with_obs(tiny_gpt, **over):
    now, clock = _fake_clock()
    obs = Observability(clock=clock)
    engine = _mk(tiny_gpt, obs=obs, clock=clock, **over)
    for r in _reqs():
        engine.add_request(r)
    while engine.has_work:
        engine.step()
        now[0] += 0.125           # deterministic trace timestamps
    out, _ = engine.finished, engine.statuses
    res = engine.run(return_status=True)
    return obs, engine, res


def test_chrome_trace_roundtrips_and_is_monotone_per_tid(tiny_gpt):
    obs, engine, res = _drive_with_obs(tiny_gpt)
    assert engine.stats()["num_preemptions"] >= 1   # the tight pool bit
    ct = json.loads(json.dumps(obs.tracer.chrome_trace()))
    evs = ct["traceEvents"]
    assert any(e["ph"] == "M" and e["args"].get("name") == "engine"
               for e in evs)
    phs = {e["ph"] for e in evs}
    assert {"B", "E", "X", "i", "M"} <= phs
    last = {}
    for e in evs:
        if e["ph"] == "M":
            continue
        assert e["ts"] >= last.get(e["tid"], -1.0), e
        last[e["tid"]] = e["ts"]
        assert e["pid"] == 1
    # under the injectable clock the timestamps are exact multiples of
    # the fake tick (deterministic traces)
    for e in evs:
        if e["ph"] != "M":
            assert (e["ts"] / 1e6 * 8) == pytest.approx(
                round(e["ts"] / 1e6 * 8), abs=1e-6)


def test_preempted_timeline_contiguous_and_complete(tiny_gpt):
    obs, engine, res = _drive_with_obs(tiny_gpt)
    tls = obs.tracer.timelines()
    preempted = [uid for uid, tl in tls.items()
                 if any(e["type"] == "preempt" for e in tl)]
    assert preempted, "tight pool should have preempted someone"
    for uid, tl in tls.items():
        types = [e["type"] for e in tl]
        assert types[0] == "enqueue"
        assert types[-1] == "terminal"
        assert tl[-1]["status"] == "finished"
        # every preempt is immediately followed by its requeue, and a
        # later re-admission continues the SAME timeline (contiguity)
        for i, e in enumerate(tl):
            if e["type"] == "preempt":
                assert types[i + 1] == "requeue"
                assert "admit" in types[i + 2:], "no re-admission traced"
        # completeness: the prefill's first token + every drained
        # decode token accounts for exactly the delivered output
        drained = sum(e["tokens"] for e in tl if e["type"] == "drain")
        assert drained + 1 == len(res[uid].tokens), uid
        # timestamps never go backwards along the timeline
        ts = [e["t"] for e in tl]
        assert ts == sorted(ts)


# ---------------------------------------------------------------------------
# the zero-perturbation certification (acceptance bar)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("sampled", [False, True],
                         ids=["greedy", "sampled"])
@pytest.mark.parametrize("spec", [0, 2], ids=["plain", "speculative"])
def test_observed_engine_is_bit_identical(tiny_gpt, sampled, spec):
    """Tracing/recorder/metrics ON vs OFF, greedy + sampled,
    speculative + not, on a pool tight enough to preempt: outputs and
    statuses must match bit for bit."""
    reqs = _reqs(sampled=sampled)

    def serve(obs):
        engine = _mk(tiny_gpt, obs=obs, spec_tokens=spec)
        for r in reqs:
            engine.add_request(r)
        res = engine.run(return_status=True)
        return res, engine.stats()

    ref, ref_stats = serve(None)
    obs = Observability()
    got, got_stats = serve(obs)
    assert _results_key(got) == _results_key(ref)
    assert got_stats["num_preemptions"] == ref_stats["num_preemptions"]
    assert got_stats["num_preemptions"] >= 1
    # and the observer really observed
    m = obs.metrics.as_dict()
    assert m["serving_requests_total"] == len(reqs)
    assert m["serving_tokens_total"] == sum(
        len(r.tokens) for r in got.values())
    assert m["serving_ttft_s"]["count"] == len(reqs)
    assert m["serving_itl_s"]["count"] > 0
    assert len(obs.recorder) > 0 and len(obs.tracer) > 0


def test_snapshot_restore_cross_obs_bit_identical(tiny_gpt):
    """Snapshot taken WITH an observer restores bit-identically into an
    engine WITHOUT one (and vice versa): observer state is audit-only,
    outside the fingerprint, never reloaded."""
    reqs = _reqs(new=10, sampled=True, seed=23)

    def uninterrupted():
        engine = _mk(tiny_gpt)
        for r in reqs:
            engine.add_request(r)
        return engine.run(return_status=True)

    ref = uninterrupted()

    def interrupted(obs_first, obs_second):
        e1 = _mk(tiny_gpt, obs=obs_first)
        for r in reqs:
            e1.add_request(r)
        for _ in range(3):
            e1.step()
        snap = json.loads(json.dumps(e1.snapshot()))
        if obs_first is not None:
            assert snap["observability"]["audit_only"] is True
            assert isinstance(snap["observability"]["recorder_tail"],
                              list)
        else:
            assert "observability" not in snap
        partial = e1.run(return_status=True)
        e2 = _mk(tiny_gpt, obs=obs_second)
        e2.restore(snap)
        rest = e2.run(return_status=True)
        # the snapshot boundary: everything terminal before it drains
        # from e1, the rest from e2 — the union must equal the
        # uninterrupted run
        merged = dict(rest)
        for uid, r in partial.items():
            if uid not in merged or snap["statuses"].get(uid):
                merged[uid] = r
        return {u: merged[u] for u in ref}

    with_obs = interrupted(Observability(), None)
    assert _results_key(with_obs) == _results_key(ref)
    obs2 = Observability()
    into_obs = interrupted(None, obs2)
    assert _results_key(into_obs) == _results_key(ref)
    # the restoring observer recorded the restore event
    assert any(e["kind"] == "restore" for e in obs2.recorder.tail())


# ---------------------------------------------------------------------------
# incident paths: quarantine tails, stall, crash dump
# ---------------------------------------------------------------------------


def test_quarantine_freezes_recorder_incident(tiny_gpt):
    plan = FaultPlan([FaultSpec(site="decode", kind="transient",
                                every=1)])
    obs = Observability()
    model, params = tiny_gpt
    engine = InferenceEngine(
        model, params,
        EngineConfig(max_batch=2, block_size=8, num_blocks=16,
                     max_prefill_len=8, max_seq_len=32,
                     max_dispatch_retries=1),
        faults=plan, obs=obs)
    for r in _reqs(n=2, new=4):
        engine.add_request(r)
    res = engine.run(return_status=True)
    assert all(r.status == "failed" for r in res.values())
    incidents = [i for i in obs.recorder.incidents
                 if i["label"] == "quarantine"]
    assert incidents and incidents[0]["events"], \
        "quarantine must freeze a recorder tail"
    kinds = {e["kind"] for e in obs.recorder.tail()}
    assert "fault_retry" in kinds and "quarantine" in kinds
    # the trace shows the terminal failure too
    for uid in res:
        tl = obs.tracer.request_timeline(uid)
        assert tl[-1] == {**tl[-1], "type": "terminal",
                          "status": "failed"}


def test_engine_stalled_error_carries_recorder_tail():
    err = EngineStalledError("m", {"k": 1},
                             recorder_tail=[{"kind": "tick"}])
    assert err.recorder_tail == [{"kind": "tick"}]
    assert err.engine_stats == {"k": 1}
    assert EngineStalledError("m", {}).recorder_tail is None


def test_unhandled_run_exception_writes_crash_dump(tiny_gpt, tmp_path):
    dump_path = tmp_path / "crash.json"
    plan = FaultPlan([FaultSpec(site="decode", kind="crash", at=(1,))])
    obs = Observability(crash_dump_path=str(dump_path))
    model, params = tiny_gpt
    engine = InferenceEngine(
        model, params, EngineConfig(**TIGHT_KW), faults=plan, obs=obs)
    for r in _reqs(n=2, new=6):
        engine.add_request(r)
    with pytest.raises(SimulatedCrash):
        engine.run()
    assert dump_path.exists()
    dump = json.loads(dump_path.read_text())
    assert dump["format"] == "apex_tpu-obs-dump-v1"
    assert "SimulatedCrash" in dump["error"]
    assert any(i["label"] == "crash"
               for i in dump["recorder"]["incidents"])
    # and the offline summarizer reads the crash dump directly
    ts = _load_trace_summary()
    report = ts.summarize_file(str(dump_path))
    assert "CRASH DUMP" in report


# ---------------------------------------------------------------------------
# the offline summarizer (satellite)
# ---------------------------------------------------------------------------


def _load_trace_summary():
    path = (Path(__file__).resolve().parents[1] / "tools"
            / "trace_summary.py")
    spec = importlib.util.spec_from_file_location("_trace_summary", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_trace_summary_reports_lifecycle_and_tallies(tiny_gpt, tmp_path):
    obs, engine, res = _drive_with_obs(tiny_gpt)
    p = tmp_path / "dump.json"
    obs.dump_to(str(p))
    ts = _load_trace_summary()
    report = ts.summarize_file(str(p))
    for uid in res:
        assert f"{uid}: finished" in report
    assert "preemptions" in report
    assert "-- shed tally: none" in report
    assert "-- ladder timeline: no transitions" in report
    assert "serving_ttft_s p50=" in report
    assert ts.main([str(p)]) == 0


def test_trace_summary_counts_sheds(tiny_gpt):
    now, clock = _fake_clock()
    obs = Observability(clock=clock)
    engine = _mk(tiny_gpt, obs=obs, clock=clock, max_waiting=1)
    reqs = _reqs(n=3, new=4)
    engine.add_request(reqs[0])
    assert not engine.try_add(reqs[1])        # queue_full door shed
    assert not engine.try_add(reqs[2])
    engine.run()
    ts = _load_trace_summary()
    report = ts.summarize(obs.dump())
    assert "queue_full=2" in report
    assert obs.metrics.as_dict()["serving_sheds_total"] == 2


# ---------------------------------------------------------------------------
# stats(deep=True) + TrainLoop observability
# ---------------------------------------------------------------------------


def test_stats_deep_merges_observability(tiny_gpt):
    obs = Observability()
    engine = _mk(tiny_gpt, obs=obs)
    for r in _reqs(n=2, new=4):
        engine.add_request(r)
    engine.run()
    shallow = engine.stats()
    assert "observability" not in shallow
    deep = engine.stats(deep=True)
    o = deep["observability"]
    assert o["metrics"]["serving_requests_total"] == 2
    assert o["trace_events"] > 0 and o["recorder_events"] > 0
    # exposition is scrapable text
    text = obs.metrics.exposition()
    assert "# TYPE serving_ttft_s histogram" in text
    assert 'serving_ttft_s_bucket{le="+Inf"} 2' in text
    # no observer -> deep adds nothing
    assert "observability" not in _mk(tiny_gpt).stats(deep=True)


class _FakeState:
    step = 0


def test_trainloop_observability_watchdog_and_metrics():
    obs = Observability()
    losses = iter([1.0, float("nan"), float("nan"), float("nan"), 1.0])

    def fake_step(state, batch):
        return state, {"loss": next(losses)}

    loop = TrainLoop(fake_step, _FakeState(),
                     watchdog=WatchdogConfig(skip_steps=2,
                                             rescale_steps=0),
                     obs=obs)
    with pytest.raises(Exception) as ei:
        loop.run(range(5))
    assert "non-finite" in str(ei.value)
    m = obs.metrics.as_dict()
    # all 5 dispatches count (the halt raises at the 5th step's
    # deferred fetch of step 4's nan — after the dispatch was timed)
    assert m["train_steps_total"] == 5
    assert m["train_nonfinite_total"] == 3
    assert m["train_step_s"]["count"] == 5
    actions = [e["action"] for e in obs.recorder.tail()
               if e["kind"] == "watchdog"]
    assert actions == ["skip", "skip", "halt"]
    assert any(i["label"] == "watchdog_halt"
               for i in obs.recorder.incidents)
    deep = loop.stats(deep=True)
    assert deep["observability"]["metrics"]["train_steps_total"] == 5
    assert "observability" not in loop.stats()


def test_trainloop_mesh_rides_records_and_summary():
    """A sharded train step (one exposing ``mesh_shape``) stamps the
    mesh into every ``train_step`` recorder event, and the offline
    summarizer renders the sharded-train line from the dump."""
    obs = Observability()

    def fake_step(state, batch):
        return state, {"loss": 1.0}

    fake_step.mesh_shape = (2, 1)
    loop = TrainLoop(fake_step, _FakeState(), obs=obs)
    loop.run(range(3))
    evs = [e for e in obs.recorder.tail() if e["kind"] == "train_step"]
    assert len(evs) == 3
    assert all(e["mesh"] == [2, 1] for e in evs)
    report = _load_trace_summary().summarize(obs.dump())
    assert "-- sharded train: 3/3 steps" in report
    assert "(batch, model)=(2x1) mesh" in report


def test_trainloop_without_obs_unchanged():
    losses = iter(float(i) for i in range(6))

    def fake_step(state, batch):
        return state, {"loss": next(losses)}

    loop = TrainLoop(fake_step, _FakeState())
    out = loop.run(range(3))
    assert [m["loss"] for m in out] == [0.0, 1.0, 2.0]
    assert loop.stats()["steps_dispatched"] == 3
